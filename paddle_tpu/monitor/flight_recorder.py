"""Collective flight recorder: per-rank ring buffer + desync diagnoser.

The NCCL-flight-recorder analog for the store-backed collective
transport (distributed/process_group.py): every eager collective a rank
issues is recorded as ``(seq, op, reduce_op, shape, dtype, axis,
t_start, t_end)`` in a fixed-capacity ring. When a collective times out
— the classic symptom of a desynchronized call stream (T3 / rank skew /
one rank wedged in a different op) — the timing-out rank:

1. dumps its own ring buffer into the TCPStore (the store is alive; it
   is the *peer's contribution* that never arrived),
2. waits a short grace window for the other ranks' dumps (they time out
   on their own stuck op around the same time),
3. diagnoses the gathered call streams: the first sequence position
   where per-rank op signatures diverge, and which rank(s) diverge from
   the majority — ranks that posted no dump are reported missing,
4. writes a postmortem JSON report (``PT_MONITOR_DUMP_DIR``, default
   cwd) and re-raises the timeout with the diagnosis attached.

Everything here is stdlib-only (no jax, no numpy) so worker processes
can run it without touching an accelerator backend.
"""
from __future__ import annotations

import json
import os
import threading
import time


class FlightRecorder:
    """Fixed-capacity ring buffer of collective call records."""

    def __init__(self, capacity=None):
        if capacity is None:
            capacity = int(os.environ.get("PT_FR_CAPACITY", "512"))
        self.capacity = max(int(capacity), 1)
        self.enabled = os.environ.get("PT_FR", "1").lower() \
            not in ("0", "false", "off")
        self._lock = threading.Lock()
        self._buf = []
        self._seq = 0
        self._gseqs = {}    # group -> per-group sequence counter
        self._depth = threading.local()

    # -- recording ---------------------------------------------------------

    def record(self, op, reduce_op=None, shape=None, dtype=None,
               axis=None, group=None, strict_shape=False):
        """Context manager recording one collective. Nested collectives
        (allreduce lowers to allgather on the store transport) record
        only the OUTERMOST call — that is the stream that must match
        across ranks. ``strict_shape=True`` marks ops whose local shape
        must agree across ranks (allreduce, reduce_scatter, alltoall) so
        the diagnoser can flag shape skew; ops with legitimately
        rank-varying payloads (object allgather/broadcast, scatter)
        leave it False and match on the op stream only."""
        return _Record(self, op, reduce_op, shape, dtype, axis, group,
                       strict_shape)

    def _begin(self, op, reduce_op, shape, dtype, axis, group,
               strict_shape):
        with self._lock:
            seq = self._seq
            self._seq += 1
            # per-group sequence: subgroup collectives advance the
            # global seq only on member ranks, so cross-rank alignment
            # must happen within one group's stream (gseq), never on
            # the global counter
            gseq = self._gseqs.get(group, 0)
            self._gseqs[group] = gseq + 1
            entry = {
                "seq": seq,
                "gseq": gseq,
                "op": op,
                "reduce_op": reduce_op,
                "shape": list(shape) if shape is not None else None,
                "dtype": str(dtype) if dtype is not None else None,
                "axis": axis,
                "group": group,
                "strict_shape": bool(strict_shape),
                "t_start": time.time(),
                "t_end": None,
            }
            self._buf.append(entry)
            if len(self._buf) > self.capacity:
                del self._buf[:len(self._buf) - self.capacity]
        return entry

    def _end(self, entry):
        entry["t_end"] = time.time()

    def note_event(self, kind, **data):
        """Append a structured NON-collective event to the ring (perf
        sentinels drop anomaly events here so a postmortem ring dump
        interleaves "loss went NaN at t" with the collective stream).
        Events do NOT advance ``seq``/``gseq`` and carry
        ``group="__events"`` — they are invisible to the cross-rank
        stream diagnosis, which compares collective call streams only
        (an anomaly firing on one rank must never read as a desync)."""
        if not self.enabled:
            return None
        entry = {
            "event": kind,
            "seq": None,
            "gseq": None,
            "op": "event:%s" % kind,
            "group": "__events",
            "t_start": time.time(),
            "t_end": None,
            "data": dict(data),
        }
        with self._lock:
            self._buf.append(entry)
            if len(self._buf) > self.capacity:
                del self._buf[:len(self._buf) - self.capacity]
        return entry

    def note_bytes(self, nbytes):
        """Attribute wire payload bytes to the currently-open outermost
        record on this thread (the store transport calls this from its
        put/get plumbing): entries accumulate a ``wire_bytes`` field so
        a postmortem ring dump shows the ACTUAL encoded payload sizes —
        including the compressed sizes when the quantized wire format
        (distributed/compress.py) is active. Never part of the
        cross-rank signature (payload framing may legitimately differ
        by rank)."""
        entry = getattr(self._depth, "entry", None)
        if entry is not None:
            entry["wire_bytes"] = entry.get("wire_bytes", 0) + int(nbytes)

    # -- inspection --------------------------------------------------------

    def entries(self):
        with self._lock:
            return [dict(e) for e in self._buf]

    def clear(self):
        with self._lock:
            self._buf = []
            self._seq = 0
            self._gseqs = {}

    def dump(self, rank=None, world_size=None):
        return {
            "rank": rank,
            "world_size": world_size,
            "capacity": self.capacity,
            "next_seq": self._seq,
            "entries": self.entries(),
        }


class _Record:
    __slots__ = ("_fr", "_args", "_entry", "_outer")

    def __init__(self, fr, *args):
        self._fr = fr
        self._args = args
        self._entry = None

    def __enter__(self):
        fr = self._fr
        d = fr._depth
        depth = getattr(d, "n", 0)
        d.n = depth + 1
        self._outer = depth == 0
        if fr.enabled and self._outer:
            self._entry = fr._begin(*self._args)
            d.entry = self._entry  # note_bytes target for nested I/O
        return self._entry

    def __exit__(self, *exc):
        d = self._fr._depth
        d.n -= 1
        if self._entry is not None:
            d.entry = None
            self._fr._end(self._entry)


_recorder = None
_rec_lock = threading.Lock()


def get_flight_recorder():
    global _recorder
    if _recorder is None:
        with _rec_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


# -- desync diagnosis --------------------------------------------------------

def signature(entry):
    """The part of a record that must match across ranks at each seq.
    Shape/dtype participate only for strict_shape ops — object
    collectives carry legitimately rank-varying payload sizes."""
    if entry is None:
        return None
    sig = (entry.get("op"), entry.get("reduce_op"), entry.get("axis"),
           entry.get("group"))
    if entry.get("strict_shape"):
        sig += (tuple(entry.get("shape") or ()), entry.get("dtype"))
    return sig


def diagnose(buffers, world_size=None, group=None):
    """Find the first call-stream divergence across per-rank buffers.

    ``buffers``: {rank: [entry, ...]} — ranks that produced no dump may
    simply be absent. When ``group`` is given (the process group whose
    collective timed out), comparison is scoped to that group's stream
    and aligned on the per-group sequence (``gseq``): subgroup
    collectives advance the GLOBAL counter only on member ranks, so
    global-seq alignment would shift streams and blame healthy ranks.
    Returns a report dict:

      status            "desync" | "consistent"
      first_divergence_seq   (g)seq number of the first mismatching call
      diverging_ranks   ranks whose signature differs from the majority
                        (or whose stream already ended)
      missing_ranks     ranks (0..world_size-1) with no dump at all
      expected / observed    majority signature vs per-rank signatures
    """
    # event entries (note_event: perf anomalies etc.) carry no sequence
    # number and are rank-local by nature — drop them before alignment
    # so a one-rank anomaly can never masquerade as a stream divergence
    buffers = {int(r): [e for e in b
                        if not e.get("event") and e.get("seq") is not None]
               for r, b in buffers.items()}
    missing = []
    if world_size:
        missing = [r for r in range(world_size) if r not in buffers]
    report = {"status": "consistent", "world_size": world_size,
              "group": group,
              "ranks_reporting": sorted(buffers), "missing_ranks": missing,
              "first_divergence_seq": None, "diverging_ranks": [],
              "expected": None, "observed": None}
    if not buffers:
        report["status"] = "no-data"
        return report
    # align by (per-group) SEQUENCE NUMBER, not list position: rings of
    # different ranks may have wrapped at different times. A seq below a
    # rank's oldest retained entry was evicted — unknown, never evidence
    # of desync; a seq past a rank's newest entry means its call stream
    # ENDED there — that is the divergence signal.
    if group is not None:
        by_seq = {r: {e.get("gseq", e["seq"]): e for e in b
                      if e.get("group") == group}
                  for r, b in buffers.items()}
    else:
        by_seq = {r: {e["seq"]: e for e in b} for r, b in buffers.items()}
    bounds = {r: ((min(d), max(d)) if d else None)
              for r, d in by_seq.items()}
    all_seqs = sorted({s for d in by_seq.values() for s in d})
    for s in all_seqs:
        sigs = {}
        for r, d in by_seq.items():
            if bounds[r] is not None and s < bounds[r][0]:
                continue            # evicted from this rank's ring
            sigs[r] = signature(d.get(s))
        distinct = set(sigs.values())
        if len(distinct) <= 1:
            continue
        # majority signature = the stream most ranks agree on
        counts = {}
        for v in sigs.values():
            if v is not None:
                counts[v] = counts.get(v, 0) + 1
        expected = max(counts, key=counts.get)
        diverging = sorted(r for r, v in sigs.items() if v != expected)
        report.update({
            "status": "desync",
            "first_divergence_seq": s,
            "diverging_ranks": diverging,
            "expected": list(expected),
            "observed": {str(r): (list(v) if v is not None else None)
                         for r, v in sigs.items()},
        })
        return report
    # identical streams from every reporting rank: a missing rank (never
    # dumped — wedged outside collectives or dead) is the suspect
    if missing:
        report["status"] = "desync"
        report["diverging_ranks"] = missing
        report["first_divergence_seq"] = (all_seqs[-1] if all_seqs
                                          else None)
    return report


# -- hang-time store exchange ------------------------------------------------

_FR_PREFIX = "__fr"


def dump_to_store(store, rank, world_size, recorder=None, prefix=None):
    """Publish this rank's ring buffer for postmortem gathering. The
    dump is stamped with its wall-clock time: keys are fixed per rank
    (ranks cannot coordinate a per-incident nonce while desynced), so
    freshness is what separates THIS incident's dump from a previous
    incident's leftover on the same store."""
    rec = recorder or get_flight_recorder()
    key = "%s/rank%d" % (prefix or _FR_PREFIX, rank)
    payload = rec.dump(rank, world_size)
    payload["dumped_at"] = time.time()
    store.set(key, json.dumps(payload).encode())
    return key


def gather_from_store(store, world_size, grace_s=5.0, prefix=None,
                      fresh_within_s=None):
    """Collect whatever per-rank dumps appear within the grace window.

    Barrier-free by design: a wedged rank never dumps, and the gather
    must not hang on it — absence is itself the diagnostic signal.
    Dumps older than ``fresh_within_s`` (a previous incident on the
    same store) are ignored; a rank timing out NOW overwrites its key,
    so polling continues until a fresh dump lands or the grace window
    closes."""
    prefix = prefix or _FR_PREFIX
    if fresh_within_s is None:
        fresh_within_s = max(10 * grace_s, 60.0)
    deadline = time.monotonic() + grace_s
    buffers = {}
    pending = set(range(world_size))
    while pending and time.monotonic() < deadline:
        for r in sorted(pending):
            left = deadline - time.monotonic()
            data = store.get("%s/rank%d" % (prefix, r),
                             timeout_s=max(min(left, 0.25), 0.05))
            if data is not None:
                try:
                    payload = json.loads(data.decode())
                    dumped_at = payload.get("dumped_at")
                    # ptlint: clock-ok — cross-rank freshness has only
                    # the shared wall clock; the window is coarse
                    # (seconds) so an NTP step degrades, not breaks it
                    now_wall = time.time()
                    if dumped_at is not None and \
                            now_wall - dumped_at > fresh_within_s:
                        continue    # stale: a previous incident's dump
                    buffers[r] = payload["entries"]
                except Exception:
                    buffers[r] = []
                pending.discard(r)
    return buffers


def on_collective_timeout(store, rank, world_size, waited_key=None,
                          recorder=None, grace_s=None, dump_dir=None,
                          group=None):
    """Full hang/desync postmortem: dump own buffer, gather peers,
    diagnose, persist the report. ``group`` (the timing-out process
    group's prefix) scopes both the dump-key namespace — a subgroup
    uses group-LOCAL rank numbering that must not collide with the
    world group's keys — and the stream comparison. Never raises —
    this runs inside an exception path and must not mask the original
    TimeoutError."""
    try:
        rec = recorder or get_flight_recorder()
        if not rec.enabled:
            return None
        if grace_s is None:
            grace_s = float(os.environ.get("PT_FR_GRACE_S", "5"))
        prefix = _FR_PREFIX if group is None \
            else "%s/%s" % (_FR_PREFIX, group)
        dump_to_store(store, rank, world_size, rec, prefix=prefix)
        buffers = gather_from_store(store, world_size, grace_s,
                                    prefix=prefix)
        report = diagnose(buffers, world_size, group=group)
        report["detected_by_rank"] = rank
        report["waited_key"] = waited_key
        report["buffers"] = buffers
        d = dump_dir or os.environ.get("PT_MONITOR_DUMP_DIR") or "."
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, "flight_recorder_rank%d.json" % rank)
            with open(path, "w") as f:
                json.dump(report, f, indent=1)
                f.write("\n")
            report["report_path"] = path
        except OSError:
            pass
        return report
    except Exception:
        return None


def summarize(report):
    """One-line human summary for exception messages."""
    if not report:
        return "flight recorder unavailable"
    if report.get("status") == "desync":
        return ("collective desync: first divergence at seq %s, "
                "diverging rank(s) %s (report: %s)"
                % (report.get("first_divergence_seq"),
                   report.get("diverging_ranks"),
                   report.get("report_path", "not written")))
    return ("no call-stream divergence detected across %s reporting "
            "rank(s); likely a straggler or network stall"
            % len(report.get("ranks_reporting", [])))
