"""Span journal: per-request & per-step trace timelines + exemplars.

The monitor stack can say *that* a step ran (registry), *that* a rank
hung (watchdog/flight recorder), and *whether* the step was efficient
(perf) — but not tell the story of any single request or step: a p99
TTFT outlier is an anonymous histogram bucket with no way back to the
request it was, where its time went (queue vs prefill vs
preemption-recompute vs decode), or which collective it sat behind.
This module is that missing journey layer:

1. **Journal** — a bounded, lock-cheap store of *traces* (one per
   request / per train job), each a list of *spans* (``span_id``,
   ``parent_id``, ``kind``, wall ``t_start``/``t_end``, attrs) carrying
   typed *events* (``(ts, name, attrs)``). The serving engine gives
   every request a trace at arrival and drives contiguous *phase*
   spans (``queue → prefill → decode → preempted → prefill(resume) →
   decode``) whose durations sum to the request's e2e latency; the
   compiled train steps record per-step spans whose child *comm* spans
   replay the flight-recorder brackets (seq/gseq-linked, so a trace
   and a desync postmortem name the same collective).

2. **Exemplars** — an OpenMetrics-style bucket→trace-id map: while a
   trace context is set (``exemplar_context``), every Histogram
   observation also records ``{bucket: (trace_id, value, ts)}`` through
   a registry hook slot (``_state.ex_hook``), so the TTFT histogram's
   p99 bucket resolves to the exact request's span timeline.

3. **Export** — ``/debugz/trace`` (journal summary + exemplars) and
   ``/debugz/trace/{id}`` (one trace's full timeline) on the fleet KV
   HTTP server; ``write_journal`` persists the journal with a
   wall↔monotonic clock anchor so ``tools/trace_merge.py --requests``
   can merge request spans into the rank-prefixed chrome-trace
   timeline one Perfetto view reads end-to-end.

4. **Cross-process context** — ``format_traceparent`` /
   ``parse_traceparent`` carry ``(trace_id, parent_span_id)`` over the
   fleet HTTP protocol (``pt1-<trace_id>-<span_id hex>``); the
   receiving process ``adopt_trace``s the incoming id and opens its
   spans with ``remote_parent=`` (parent span ids from another
   process's id space never mix with local ``parent_id`` counters), so
   the serving router's dispatch span and the replica engine's phase
   spans land under ONE fleet-wide trace id and
   ``monitor/trace_merge.merge_fleet_journals`` can stitch them. Ids
   are ``<cid>.<counter>`` with a per-process random 64-bit cid — pids
   collide across hosts and recycle within one; random cids don't.

Discipline (the PR-2/5 contract, test-pinned by tests/test_trace.py):
default OFF via ``FLAGS_monitor_trace``; while off the hot paths are
native-call-free and thread-free — emitters early-return on one
attribute load + branch, the registry exemplar hook slot stays
``None``, and nothing is allocated into the journal. Stdlib-only so
worker processes can import it without an accelerator backend.
"""
from __future__ import annotations

import os
import threading
import time

from . import registry as _registry
from .timeseries import _flag

DEFAULT_CAPACITY = 256          # retained traces
DEFAULT_SPANS_PER_TRACE = 512   # per-trace span ring (train jobs loop)
_EVENTS_PER_SPAN = 256


class _TraceState:
    __slots__ = ("enabled", "capacity", "span_cap", "lock", "traces",
                 "open_spans", "next_trace", "next_span", "exemplars",
                 "jobs", "cid")

    def __init__(self):
        self.enabled = False
        # per-process random 64-bit collector id (the store-nonce
        # discipline): trace ids minted off the pid collide across
        # hosts AND recycle within one, silently fusing unrelated
        # requests in fleet-merged journals
        self.cid = "%016x" % int.from_bytes(os.urandom(8), "little")
        self.capacity = int(os.environ.get("PT_TRACE_CAPACITY",
                                           str(DEFAULT_CAPACITY)))
        self.span_cap = int(os.environ.get("PT_TRACE_SPANS_PER_TRACE",
                                           str(DEFAULT_SPANS_PER_TRACE)))
        self.lock = threading.Lock()
        self.traces = {}        # trace_id -> trace dict (insertion order)
        self.open_spans = {}    # span_id -> span dict (unfinished)
        self.next_trace = 0
        self.next_span = 0
        # {series_name: {bucket_label: {trace_id, value, ts}}}
        self.exemplars = {}
        # train-step recorder state: job -> {trace_id, fr_seq watermark}
        self.jobs = {}


_state = _TraceState()
_tls = threading.local()


def now():
    """The journal's timebase: wall clock (``time.time()``) — the same
    base the flight recorder stamps entries with, so comm child spans
    replayed from its ring land on the step span without conversion."""
    return time.time()


# -- lifecycle ---------------------------------------------------------------

def enable(capacity=None, span_cap=None):
    """Turn the journal on (process-wide) and install the registry
    exemplar hook. Idempotent; capacities only affect future records."""
    if capacity is not None:
        _state.capacity = max(int(capacity), 1)
    if span_cap is not None:
        _state.span_cap = max(int(span_cap), 8)
    _state.enabled = True
    _registry._state.ex_hook = _ex_hook
    return _state


def disable():
    """Stop recording: the exemplar hook slot returns to ``None`` so
    the Histogram hot path is exactly the disabled-from-boot one.
    Recorded traces are kept (inspectable post-incident); ``clear()``
    drops them."""
    _state.enabled = False
    _registry._state.ex_hook = None


def is_enabled():
    return _state.enabled


def clear():
    with _state.lock:
        _state.traces = {}
        _state.open_spans = {}
        _state.exemplars = {}
        _state.jobs = {}


# -- journal writes ----------------------------------------------------------

def _evict_locked():
    """Drop oldest traces past capacity — finished ones first, but
    bounded beats complete: an all-open journal still evicts."""
    while len(_state.traces) > _state.capacity:
        victim = None
        for tid, tr in _state.traces.items():
            if tr["open"] == 0:
                victim = tid
                break
        if victim is None:
            victim = next(iter(_state.traces))
        tr = _state.traces.pop(victim)
        for s in tr["spans"]:
            _state.open_spans.pop(s["span_id"], None)


def new_trace(name, t=None, **attrs):
    """Create a trace; returns its id (None while disabled — every
    later call taking a trace/span id no-ops on None, so a mid-run
    flag flip never half-traces a request)."""
    if not _state.enabled:
        return None
    if t is None:
        t = now()
    with _state.lock:
        tid = "%s.%x" % (_state.cid, _state.next_trace)
        _state.next_trace += 1
        _state.traces[tid] = {
            "trace_id": tid,
            "name": name,
            "attrs": dict(attrs),
            "t_start": t,
            "spans": [],
            "open": 0,
        }
        _evict_locked()
    return tid


def adopt_trace(trace_id, name, t=None, **attrs):
    """Register a trace minted by ANOTHER process — the id arrived in a
    traceparent context over the wire — so local spans land under the
    same fleet-wide id. Idempotent: re-adopting an id (or adopting one
    this process minted) just merges attrs; returns the id, or None
    while disabled so callers keep the new_trace() contract."""
    if not _state.enabled or trace_id is None:
        return None
    if t is None:
        t = now()
    with _state.lock:
        tr = _state.traces.get(trace_id)
        if tr is not None:
            if attrs:
                tr["attrs"].update(attrs)
            return trace_id
        _state.traces[trace_id] = {
            "trace_id": trace_id,
            "name": name,
            "attrs": dict(attrs, adopted=True),
            "t_start": t,
            "spans": [],
            "open": 0,
        }
        _evict_locked()
    return trace_id


def start_span(name, trace_id, parent_id=None, kind="span", t=None,
               remote_parent=None, **attrs):
    """Open a span under ``trace_id``; returns its span id (None when
    disabled, the trace id is None, or the trace was evicted).
    ``remote_parent`` names a parent span id from ANOTHER process's id
    space (extracted from a traceparent context) — kept separate from
    ``parent_id`` because local span ids and remote ones never share a
    counter; the fleet merge stitches on it."""
    if not _state.enabled or trace_id is None:
        return None
    if t is None:
        t = now()
    with _state.lock:
        tr = _state.traces.get(trace_id)
        if tr is None:
            return None
        sid = _state.next_span
        _state.next_span += 1
        span = {
            "span_id": sid,
            "trace_id": trace_id,
            "parent_id": parent_id,
            "name": name,
            "kind": kind,
            "t_start": t,
            "t_end": None,
            "attrs": dict(attrs),
            "events": [],
        }
        if remote_parent is not None:
            span["remote_parent"] = remote_parent
        if len(tr["spans"]) >= _state.span_cap:
            # per-trace span ring (a long-lived train trace must stay
            # bounded): drop the oldest FINISHED span; when everything
            # is somehow open, drop the oldest anyway
            drop = next((i for i, s in enumerate(tr["spans"])
                         if s["t_end"] is not None), 0)
            dead = tr["spans"].pop(drop)
            if dead["t_end"] is None:
                tr["open"] -= 1
                _state.open_spans.pop(dead["span_id"], None)
        tr["spans"].append(span)
        tr["open"] += 1
        _state.open_spans[sid] = span
    return sid


def end_span(span_id, t=None, **attrs):
    if span_id is None:
        return
    if t is None:
        t = now()
    with _state.lock:
        span = _state.open_spans.pop(span_id, None)
        if span is None:
            return
        span["t_end"] = t
        if attrs:
            span["attrs"].update(attrs)
        tr = _state.traces.get(span["trace_id"])
        if tr is not None:
            tr["open"] -= 1


def add_event(span_id, name, t=None, **attrs):
    """Typed event on an OPEN span (bounded per span)."""
    if span_id is None or not _state.enabled:
        return
    if t is None:
        t = now()
    with _state.lock:
        span = _state.open_spans.get(span_id)
        if span is None or len(span["events"]) >= _EVENTS_PER_SPAN:
            return
        span["events"].append({"ts": t, "name": name,
                               "attrs": dict(attrs)})


class _NoopSpan:
    """Shared disabled-path context manager: zero allocations."""

    __slots__ = ()
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _SpanCtx:
    __slots__ = ("span_id", "_pushed")

    def __init__(self, name, trace_id, parent_id, kind, attrs):
        self.span_id = start_span(name, trace_id, parent_id=parent_id,
                                  kind=kind, **attrs)
        self._pushed = False

    def __enter__(self):
        if self.span_id is not None:
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            stack.append(self.span_id)
            self._pushed = True
        return self

    def __exit__(self, *exc):
        if self._pushed:
            _tls.stack.pop()
        end_span(self.span_id)
        return False


def span(name, trace_id=None, parent_id=None, kind="span", **attrs):
    """Scoped span context manager. ``trace_id`` defaults to the
    thread's current exemplar/trace context; the parent defaults to the
    innermost enclosing ``span()`` on this thread."""
    if not _state.enabled:
        return _NOOP
    if trace_id is None:
        trace_id = current_trace_id()
    if trace_id is None:
        return _NOOP
    if parent_id is None:
        stack = getattr(_tls, "stack", None)
        if stack:
            parent_id = stack[-1]
    return _SpanCtx(name, trace_id, parent_id, kind, attrs)


# -- cross-process context (traceparent) -------------------------------------

TRACEPARENT_VERSION = "pt1"


def format_traceparent(trace_id, span_id=None):
    """Serialize ``(trace_id, parent_span_id)`` for the wire:
    ``pt1-<trace_id>-<span_id hex>`` (span id empty when the sender has
    no journal span open). Returns None for a None trace id so a
    journal-off sender emits NO context field — the flags-off wire
    format stays bit-identical."""
    if trace_id is None:
        return None
    if span_id is None:
        return "%s-%s-" % (TRACEPARENT_VERSION, trace_id)
    return "%s-%s-%x" % (TRACEPARENT_VERSION, trace_id, span_id)


def parse_traceparent(value):
    """``(trace_id, parent_span_id)`` from a wire value; ``(None,
    None)`` for absent/foreign-version/malformed input — a bad peer
    must never break admission, just lose its trace linkage."""
    if not value or not isinstance(value, str):
        return (None, None)
    parts = value.split("-")
    if len(parts) != 3 or parts[0] != TRACEPARENT_VERSION or \
            not parts[1]:
        return (None, None)
    sid = None
    if parts[2]:
        try:
            sid = int(parts[2], 16)
        except ValueError:
            return (None, None)
    return (parts[1], sid)


# -- trace context + exemplars -----------------------------------------------

def current_trace_id():
    ctx = getattr(_tls, "trace", None)
    return ctx[-1] if ctx else None


class _ExemplarCtx:
    __slots__ = ("_tid",)

    def __init__(self, tid):
        self._tid = tid

    def __enter__(self):
        ctx = getattr(_tls, "trace", None)
        if ctx is None:
            ctx = _tls.trace = []
        ctx.append(self._tid)
        return self

    def __exit__(self, *exc):
        _tls.trace.pop()
        return False


def exemplar_context(trace_id):
    """Bind ``trace_id`` as the thread's current trace: Histogram
    observations inside the block record bucket exemplars pointing at
    it (and ``span()`` resolves it as the default trace). ``None`` or
    journal-off returns the shared no-op manager — zero allocations on
    the disabled path."""
    if trace_id is None or not _state.enabled:
        return _NOOP
    return _ExemplarCtx(trace_id)


def _bucket_label(buckets, value):
    for b in buckets:
        if value <= b:
            return str(b)
    return "+Inf"


def _ex_hook(metric, key, value):
    """The registry-side Histogram hook (installed only while enabled):
    record a bucket exemplar for the thread's current trace. Runs
    inline on the observe path — one tls read when no context is set."""
    tid = current_trace_id()
    if tid is None:
        return
    series = metric._series_name(key)
    label = _bucket_label(metric.buckets, value)
    with _state.lock:
        _state.exemplars.setdefault(series, {})[label] = {
            "trace_id": tid, "value": value, "ts": time.time()}


def exemplars(series=None):
    """{series: {bucket: {trace_id, value, ts}}} (or one series')."""
    with _state.lock:
        if series is not None:
            return {b: dict(e)
                    for b, e in _state.exemplars.get(series, {}).items()}
        return {s: {b: dict(e) for b, e in bs.items()}
                for s, bs in _state.exemplars.items()}


# -- train-step recorder -----------------------------------------------------

def record_train_step(job, step, dt, steps=1, tokens=0, t_end=None):
    """One compiled-engine call as a step span on the long-lived
    ``job`` trace, with child comm spans replayed from the
    flight-recorder entries recorded during the step (matched by
    SEQUENCE watermark, never timestamps — the PR-5 discipline), each
    carrying the ring's seq/gseq/group/wire_bytes so the trace and a
    desync postmortem name the same collective."""
    if not _state.enabled:
        return None
    from .flight_recorder import get_flight_recorder

    fr = get_flight_recorder()
    if t_end is None:
        t_end = now()
    t_start = t_end - max(dt, 0.0)
    st = _state.jobs.get(job)
    if st is None or st["trace_id"] not in _state.traces:
        tid = new_trace(job, kind="train")
        st = _state.jobs[job] = {"trace_id": tid, "fr_seq": None}
    tid = st["trace_id"]
    sid = start_span("%s.step" % job, tid, kind="step", t=t_start,
                     step=int(step), steps=int(steps),
                     tokens=int(tokens))
    mark, st["fr_seq"] = st["fr_seq"], fr._seq
    if sid is not None:
        for e in fr.entries():
            seq = e.get("seq")
            if seq is None or e.get("t_end") is None:
                continue
            if mark is not None:
                if seq < mark:
                    continue
            elif e["t_start"] < t_start:
                # first call for this job has no seq watermark yet:
                # fall back to the step's own wall window (ring stamps
                # and t_start share the time.time() clock) so a
                # one-shot run_steps workload still gets its comm
                # children instead of silently dropping them
                continue
            attrs = {"seq": seq, "gseq": e.get("gseq"),
                     "group": e.get("group"), "op": e.get("op"),
                     "reduce_op": e.get("reduce_op")}
            if e.get("wire_bytes"):
                attrs["wire_bytes"] = e["wire_bytes"]
            csid = start_span(e.get("op") or "collective", tid,
                              parent_id=sid, kind="comm",
                              t=e["t_start"], **attrs)
            end_span(csid, t=e["t_end"])
    end_span(sid, t=t_end)
    return sid


# -- queries -----------------------------------------------------------------

def get_trace(trace_id):
    """Deep-ish copy of one trace ({trace_id, name, attrs, spans}) or
    None."""
    with _state.lock:
        tr = _state.traces.get(trace_id)
        if tr is None:
            return None
        return {
            "trace_id": tr["trace_id"],
            "name": tr["name"],
            "attrs": dict(tr["attrs"]),
            "t_start": tr["t_start"],
            "open_spans": tr["open"],
            "spans": [dict(s, attrs=dict(s["attrs"]),
                           events=[dict(ev) for ev in s["events"]])
                      for s in tr["spans"]],
        }


def active_spans(min_age_s=None):
    """Unfinished spans with ages — the watchdog-bundle embedding:
    "rank 3 stalled while request r17 was mid-preemption-recompute".
    ``min_age_s`` keeps only spans at least that old (a stall report
    wants the long-stuck ones, not this instant's in-flight step)."""
    t = now()
    out = []
    # attr copies happen INSIDE the lock: end_span mutates the span's
    # attrs dict concurrently, and dict() over a resizing dict raises
    with _state.lock:
        for s in _state.open_spans.values():
            age = t - s["t_start"]
            if min_age_s is not None and age < min_age_s:
                continue
            out.append({
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
                "parent_id": s["parent_id"],
                "name": s["name"],
                "kind": s["kind"],
                "age_s": round(age, 3),
                "attrs": dict(s["attrs"]),
            })
    return sorted(out, key=lambda s: -s["age_s"])


def phase_breakdown(trace_id):
    """{phase: seconds} summed over the trace's ``kind="phase"`` spans
    (open phases accrue to now) — the per-request queue / prefill /
    decode / preempted attribution; ``None`` for an unknown trace."""
    tr = get_trace(trace_id)
    if tr is None:
        return None
    t = now()
    out = {}
    for s in tr["spans"]:
        if s["kind"] != "phase":
            continue
        dur = (s["t_end"] if s["t_end"] is not None else t) - s["t_start"]
        out[s["name"]] = out.get(s["name"], 0.0) + max(dur, 0.0)
    return out


def traces_summary():
    out = []
    # summarized INSIDE the lock (the active_spans discipline): span
    # lists and open counts mutate under concurrent writers
    with _state.lock:
        for tr in _state.traces.values():
            ends = [s["t_end"] for s in tr["spans"]
                    if s["t_end"] is not None]
            out.append({
                "trace_id": tr["trace_id"],
                "name": tr["name"],
                "attrs": dict(tr["attrs"]),
                "t_start": tr["t_start"],
                "t_end": max(ends) if ends and not tr["open"] else None,
                "spans": len(tr["spans"]),
                "open_spans": tr["open"],
            })
    return out


def payload():
    """The /debugz/trace JSON body."""
    return {
        "enabled": _state.enabled,
        "capacity": _state.capacity,
        "trace_count": len(_state.traces),
        "traces": traces_summary(),
        "exemplars": exemplars(),
    }


def trace_payload(trace_id):
    """The /debugz/trace/{id} JSON body, or None for an unknown id."""
    return get_trace(trace_id)


# -- journal artifact + chrome export ----------------------------------------

def dump():
    """JSON-ready journal snapshot. Carries a wall↔monotonic clock
    anchor: journal timestamps are wall-clock, the native chrome tracer
    stamps monotonic — the anchor is the same-process shift that puts
    request spans onto the native trace's timebase when merging."""
    with _state.lock:
        traces = {tid: {
            "trace_id": tr["trace_id"], "name": tr["name"],
            "attrs": dict(tr["attrs"]), "t_start": tr["t_start"],
            "open_spans": tr["open"],
            "spans": [dict(s, attrs=dict(s["attrs"]),
                           events=[dict(ev) for ev in s["events"]])
                      for s in tr["spans"]],
        } for tid, tr in _state.traces.items()}
    return {
        "kind": "trace_journal",
        "version": 1,
        "pid": os.getpid(),
        "cid": _state.cid,
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "clock_anchor": {"wall": time.time(),
                         "monotonic": time.monotonic()},
        "exemplars": exemplars(),
        "traces": traces,
    }


def write_journal(path):
    import json

    journal = dump()
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(journal, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return journal


def chrome_events_from_journal(journal, clock="wall"):
    """Journal dict -> chrome traceEvents: one pid per trace NAME, one
    tid per trace id (each request is its own track), spans as "X"
    complete events, typed events as "i" instants, parentage preserved
    in ``args``. ``clock="monotonic"`` shifts by the journal's clock
    anchor onto the native tracer's (steady-clock) timebase — the
    right choice when merging with same-process chrome traces."""
    shift = 0.0
    if clock == "monotonic":
        anchor = journal.get("clock_anchor") or {}
        if "wall" in anchor and "monotonic" in anchor:
            shift = anchor["monotonic"] - anchor["wall"]
    evs = []
    # ptlint: clock-ok — journal spans are wall-stamped by format (the
    # clock anchor converts to monotonic); export math mirrors that
    end = journal.get("clock_anchor", {}).get("wall", time.time())
    for tid, tr in sorted((journal.get("traces") or {}).items()):
        pid = tr.get("name") or "trace"
        evs.append({"ph": "M", "name": "thread_name", "pid": pid,
                    "tid": tid,
                    "args": {"name": "%s %s" % (pid, tid)}})
        for s in tr.get("spans") or ():
            t0 = s["t_start"] + shift
            t1 = (s["t_end"] if s["t_end"] is not None else end) + shift
            args = dict(s.get("attrs") or {})
            args.update({"trace_id": tid, "span_id": s["span_id"],
                         "parent_id": s.get("parent_id"),
                         "kind": s.get("kind")})
            if s.get("remote_parent") is not None:
                args["remote_parent"] = s["remote_parent"]
            if s["t_end"] is None:
                args["open"] = True
            evs.append({"ph": "X", "name": s["name"],
                        "cat": s.get("kind") or "span", "pid": pid,
                        "tid": tid, "ts": t0 * 1e6,
                        "dur": max(t1 - t0, 0.0) * 1e6, "args": args})
            for ev in s.get("events") or ():
                evs.append({"ph": "i", "s": "t", "name": ev["name"],
                            "cat": "event", "pid": pid, "tid": tid,
                            "ts": (ev["ts"] + shift) * 1e6,
                            "args": dict(ev.get("attrs") or {},
                                         span_id=s["span_id"],
                                         trace_id=tid)})
    return evs


def to_chrome_events(clock="wall"):
    """Chrome events of the LIVE journal."""
    return chrome_events_from_journal(dump(), clock=clock)


# env/FLAGS bootstrap (the timeseries discipline): a process started
# with FLAGS_monitor_trace=1 journals from the first request/step.
if _flag("FLAGS_monitor_trace"):
    enable()
