"""Fleet telemetry plane: cross-rank aggregation, stragglers, capture.

Every monitor surface so far (registry, flight recorder, watchdog,
perf, trace) stops at one process: an N-rank run is N unrelated
``/metrics`` endpoints, and the only cross-rank story the stack can
tell is a postmortem AFTER something timed out. This module is the
live fleet view the ROADMAP item-2 router and item-3 overlap work both
need:

1. **Endpoint registration** (rank side, ``announce()``): each rank
   starts the process-wide metrics server (monitor/exporter.py) and
   registers its HTTP endpoint in the existing TCPStore under
   ``__fleet/ep/rank{r}`` — the same store the flight recorder and
   watchdog already rendezvous through. ``init_parallel_env`` wires
   this automatically under ``FLAGS_monitor_fleet``.

2. **Collector** (``FleetCollector``, runnable on any rank or as a
   standalone process holding a store client): scrapes every rank's
   ``/metrics.json`` + ``/debugz/perf`` + ``/healthz`` (plus
   best-effort ``/debugz/flight`` and ``/debugz/memory``) on an interval
   and fuses them into rank-labeled fleet series — counters SUM across
   ranks, gauges keep per-rank values plus min/max/p50 fleet
   aggregates, histograms sum bucket-wise. Each scrape also estimates
   the rank's wall-clock offset NTP-style (the PR-2 trace_merge
   discipline, here over the HTTP exchange itself: the rank's
   self-reported ``unix_time`` against the request's local midpoint,
   min-RTT sample wins), so per-rank freshness/progress stamps are
   compared on ONE clock. Served at ``/debugz/fleet`` (summary),
   ``/debugz/fleet/ranks`` (per-rank table), and Prometheus
   federation-style ``/metrics/fleet``.

3. **Straggler & skew detection**: per-scrape cross-rank deltas of
   ``train_step_seconds`` (windowed mean step time per rank) against
   the fleet median — a rank persistently slower than
   ``PT_FLEET_STRAGGLER_FACTOR`` (default 2.0) x median for
   ``PT_FLEET_STRAGGLER_PERSIST`` (default 2) consecutive scrapes is
   flagged: ``fleet_straggler_total{rank}`` increments and the rank is
   named in ``/debugz/fleet`` — while the run is still healthy,
   BEFORE any collective timeout (the flight recorder only names ranks
   post-timeout). ``train_steps_total`` watermark skew rides the same
   table (``steps_behind``).

4. **Anomaly-triggered fleet capture**: when any rank's perf sentinel
   fires (its ``perf_anomalies_total`` advances / healthz turns
   degraded) or a straggler is flagged, the collector pulls
   watchdog-style bundles (``/debugz/bundle``), span-journal tails
   (``/debugz/trace/journal``), the memory breakdown
   (``/debugz/memory``) and the profiling summary incl. folded host
   stacks (``/debugz/profile``) from ALL ranks into one
   ``fleet_capture_<ts>/`` directory (manifest + per-rank artifacts)
   — a loss spike on rank 3 automatically yields fleet-wide evidence.
   ``tools/trace_merge.py --capture`` renders the merged chrome trace
   from such a capture; ``tools/fleet_top.py`` renders the live table.

Discipline (the PR-2/5/6 contract, test-pinned): default OFF via
``FLAGS_monitor_fleet``. While off, ``announce()``/``note_identity()``
are one flag-load + branch — no metrics server, no collector thread,
no store traffic, no native calls. Stdlib-only imports so bare worker
processes can load it without an accelerator backend.
"""
from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.error
import urllib.request

from . import registry as _registry
from .timeseries import _flag

_EP_PREFIX = "__fleet/ep"
_THREAD_NAME = "pt-fleet-collector"

# -- collector telemetry (shared registry discipline: every mutator
# no-ops while the monitor is disabled) --------------------------------------

_SCRAPES = _registry.counter(
    "fleet_scrapes_total", "collector scrape rounds completed")
_SCRAPE_ERRS = _registry.counter(
    "fleet_scrape_errors_total",
    "per-rank scrape failures (unreachable/medium errors)",
    labelnames=("rank",))
_STRAGGLER_TOTAL = _registry.counter(
    "fleet_straggler_total",
    "straggler episodes flagged per rank (persistently slower than "
    "the fleet median step time)", labelnames=("rank",))
_CAPTURES_TOTAL = _registry.counter(
    "fleet_captures_total", "anomaly-triggered fleet captures",
    labelnames=("reason",))
_RANKS_OK = _registry.gauge(
    "fleet_ranks_reporting", "ranks answering the last scrape round")
_RANK_INFO = _registry.gauge(
    "fleet_rank_info",
    "per-rank identity beacon (value = pid); set by parallel/engine "
    "and serving under FLAGS_monitor_fleet so scraped series resolve "
    "to a rank/host/job", labelnames=("job", "rank", "host"))


def is_enabled():
    return _flag("FLAGS_monitor_fleet")


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _local_host():
    """The address peers should dial for this rank's endpoint: explicit
    override first, then the launch-provided routable endpoint, then
    loopback (single-host worlds)."""
    host = os.environ.get("PT_FLEET_HOST")
    if host:
        return host
    ep = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
    if ":" in ep:
        return ep.partition(":")[0]
    return "127.0.0.1"


# -- rank side: endpoint registration + identity -----------------------------

class _AnnounceState:
    __slots__ = ("lock", "url", "registered")

    def __init__(self):
        self.lock = threading.Lock()
        self.url = None
        self.registered = False


_announce = _AnnounceState()


def register_endpoint(store, rank, url, job=None, **meta):
    """Publish one rank's scrape endpoint in the fleet store."""
    rec = {"url": url, "rank": int(rank), "pid": os.getpid(),
           "host": _local_host(), "registered_at": time.time()}
    if job:
        rec["job"] = job
    rec.update(meta)
    store.set("%s/rank%d" % (_EP_PREFIX, rank),
              json.dumps(rec, default=str).encode())
    return rec


def discover_endpoints(store, world_size, timeout_s=0.05):
    """{rank: endpoint record} for every rank that has announced.
    Short per-key timeout: a rank that has not announced yet is simply
    absent this round and retried next scrape."""
    out = {}
    for r in range(int(world_size)):
        data = store.get("%s/rank%d" % (_EP_PREFIX, r),
                         timeout_s=timeout_s)
        if data is None:
            continue
        try:
            rec = json.loads(data.decode())
            if rec.get("url"):
                out[r] = rec
        except Exception:
            continue
    return out


def announce(store=None, rank=None, world_size=None, job=None, port=0):
    """Start (or reuse) this process's metrics server and register its
    endpoint under ``__fleet/ep/rank{r}``. Returns the endpoint url,
    or None while ``FLAGS_monitor_fleet`` is off (the disabled path is
    one flag-load + branch: no server, no store traffic, test-pinned).
    Idempotent: repeat calls re-register the same url (a restarted
    store server gets a fresh record) but never start a second
    server."""
    if not is_enabled():
        return None
    from . import exporter as _exporter

    with _announce.lock:
        srv = _exporter.start_metrics_server(port)
        url = "http://%s:%d" % (_local_host(), srv.port)
        _announce.url = url
    if store is None:
        from ..distributed import process_group as _pg

        pg = _pg.get_world_group()
        if pg is not None:
            store, rank, world_size = pg.store, pg.rank, pg.world_size
    if store is not None and rank is not None:
        register_endpoint(store, rank, url, job=job)
        _announce.registered = True
        try:
            _RANK_INFO.labels(job=job or "rank", rank=rank,
                              host=_local_host()).set(os.getpid())
        except Exception as e:
            _registry.warn_once(
                "fleet.rank_info",
                "paddle_tpu.monitor.fleet: rank-info gauge failed "
                "(identity labels missing from fleet view): %r" % (e,))
    return url


def announced_url():
    return _announce.url


def note_identity(job):
    """Per-rank identity label on the scraped series: the train/serving
    engines call this once at construction so the collector's fused
    view can say WHICH rank/host ran which job. One flag branch while
    fleet monitoring is off."""
    if not is_enabled():
        return
    try:
        from ..distributed import process_group as _pg

        pg = _pg.get_world_group()
        rank = pg.rank if pg is not None else 0
        _RANK_INFO.labels(job=job, rank=rank,
                          host=_local_host()).set(os.getpid())
    except Exception as e:
        _registry.warn_once(
            "fleet.note_identity",
            "paddle_tpu.monitor.fleet: identity labeling failed "
            "(fused view loses job attribution for this rank): "
            "%r" % (e,))


def maybe_announce_and_collect(pg):
    """The ``init_parallel_env`` hook: under ``FLAGS_monitor_fleet``,
    announce this rank's endpoint and — on the collector rank
    (``PT_FLEET_COLLECTOR_RANK``, default 0) — start the fleet
    collector thread. One flag branch when off."""
    if not is_enabled():
        return None
    url = announce(pg.store, pg.rank, pg.world_size)
    if pg.rank == _env_int("PT_FLEET_COLLECTOR_RANK", 0):
        start_collector(store=pg.store, world_size=pg.world_size,
                        rank=pg.rank)
    return url


# -- scraping ----------------------------------------------------------------

def _http_json(url, timeout_s):
    """(payload, t0, t1, rtt_s) — the WALL stamps around the exchange
    feed the NTP-style offset estimate (the one legitimate wall-clock
    use here: comparing the peer's self-reported unix_time against our
    own wall midpoint); the round-trip DURATION is measured on the
    monotonic clock, because an NTP step mid-exchange must not produce
    a negative or kilometric RTT. Raises on transport errors; HTTP
    error codes with a JSON body (healthz 503) still parse."""
    t0 = time.time()    # ptlint: clock-ok — NTP-style offset probe
    m0 = time.monotonic()
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            body = r.read()
    except urllib.error.HTTPError as e:
        body = e.read()
    t1 = time.time()    # ptlint: clock-ok — NTP-style offset probe
    rtt_s = max(time.monotonic() - m0, 0.0)
    return json.loads(body.decode()), t0, t1, rtt_s


def fuse_snapshots(metrics_by_rank):
    """Fuse per-rank registry snapshots into rank-labeled fleet series.

    Aggregation semantics (the /debugz/fleet contract): counters SUM
    across ranks (each rank counts its own events — the fleet total is
    their union); gauges are instantaneous per-rank states, so the
    fleet keeps every per-rank value plus min/max/p50 spread (a fleet
    "sum" of gauges like mfu would be meaningless); histograms sum
    bucket-wise (counts and sums are disjoint event sets).

    Returns {name: {kind, help, series: [{labels, per_rank, fleet}]}}.
    """
    fused = {}
    for rank, mets in sorted(metrics_by_rank.items()):
        for name, m in (mets or {}).items():
            ent = fused.setdefault(name, {
                "kind": m.get("kind", "untyped"),
                "help": m.get("help", ""), "_series": {}})
            for s in m.get("series", ()):
                labels = dict(s.get("labels") or {})
                key = tuple(sorted(labels.items()))
                se = ent["_series"].setdefault(
                    key, {"labels": labels, "per_rank": {}})
                if ent["kind"] == "histogram":
                    se["per_rank"][rank] = {
                        "sum": s.get("sum", 0.0),
                        "count": s.get("count", 0),
                        "buckets": dict(s.get("buckets") or {})}
                else:
                    se["per_rank"][rank] = s.get("value", 0)
    for name, ent in fused.items():
        series = []
        for key in sorted(ent["_series"]):
            se = ent["_series"][key]
            if ent["kind"] == "histogram":
                buckets = {}
                tot_sum, tot_count = 0.0, 0
                for h in se["per_rank"].values():
                    tot_sum += float(h["sum"] or 0.0)
                    tot_count += int(h["count"] or 0)
                    for b, c in h["buckets"].items():
                        buckets[b] = buckets.get(b, 0) + int(c)
                se["fleet"] = {"sum": tot_sum, "count": tot_count,
                               "buckets": buckets}
            else:
                vals = sorted(float(v) for v in se["per_rank"].values()
                              if isinstance(v, (int, float)))
                if not vals:
                    se["fleet"] = {}
                elif ent["kind"] == "counter":
                    se["fleet"] = {"sum": sum(vals)}
                else:
                    se["fleet"] = {
                        "min": vals[0], "max": vals[-1],
                        "p50": vals[len(vals) // 2],
                        "sum": sum(vals)}
            series.append(se)
        ent["series"] = series
        del ent["_series"]
    return fused


class FleetCollector:
    """Scrape-and-fuse loop over the fleet's rank endpoints.

    ``endpoints``: {rank: url} given explicitly, or discovered from
    ``store`` + ``world_size`` (ranks announce at their own pace — a
    missing rank is retried every round). Runs on any rank or in a
    standalone process; route payloads (``/debugz/fleet*``,
    ``/metrics/fleet``) read the installed collector via
    ``get_collector()``.
    """

    def __init__(self, endpoints=None, store=None, world_size=None,
                 interval_s=None, straggler_factor=None,
                 straggler_persist=None, capture_dir=None,
                 capture_cooldown_s=None, max_captures=None,
                 http_timeout_s=None, rank=None):
        self._lock = threading.Lock()
        self._endpoints = {int(r): (u if isinstance(u, str)
                                    else u.get("url"))
                           for r, u in (endpoints or {}).items()}
        self._store = store
        self.world_size = int(world_size) if world_size \
            else (max(self._endpoints) + 1 if self._endpoints else 0)
        self.rank = rank
        self.interval_s = float(interval_s if interval_s is not None
                                else _env_float("PT_FLEET_SCRAPE_S", 2.0))
        self.straggler_factor = float(
            straggler_factor if straggler_factor is not None
            else _env_float("PT_FLEET_STRAGGLER_FACTOR", 2.0))
        self.straggler_persist = int(
            straggler_persist if straggler_persist is not None
            else _env_int("PT_FLEET_STRAGGLER_PERSIST", 2))
        self.capture_cooldown_s = float(
            capture_cooldown_s if capture_cooldown_s is not None
            else _env_float("PT_FLEET_CAPTURE_COOLDOWN_S", 60.0))
        self.max_captures = int(
            max_captures if max_captures is not None
            else _env_int("PT_FLEET_MAX_CAPTURES", 4))
        self.http_timeout_s = float(
            http_timeout_s if http_timeout_s is not None
            else _env_float("PT_FLEET_HTTP_TIMEOUT_S", 3.0))
        self.capture_dir = capture_dir \
            or os.environ.get("PT_MONITOR_DUMP_DIR") or "."
        self._ranks = {}        # rank -> per-rank scrape/derived state
        self._fused = {}
        self._stragglers = {}   # rank -> episode info (active)
        self._captures = []     # [{dir, reason, created_at, ranks}]
        self._pending_captures = []     # [(reason, detail)] behind cooldown
        self._last_capture_at = None
        self._scrapes = 0
        self._started_at = None
        self._last_scrape_at = None
        self._thread = None
        self._stop = None
        self._pool = None       # scrape-fanout executor, lazy

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._started_at = time.time()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=_THREAD_NAME, daemon=True)
        self._thread.start()
        return self

    def stop(self, snapshot_out=None):
        if self._stop is not None:
            self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        out = snapshot_out or os.environ.get("PT_FLEET_SNAPSHOT_OUT")
        if out:
            try:
                write_snapshot_artifact(out, collector=self)
            except Exception as e:
                _registry.warn_once(
                    "fleet.snapshot_artifact",
                    "paddle_tpu.monitor.fleet: final snapshot "
                    "artifact write failed (%s): %r" % (out, e))

    def is_running(self):
        return self._thread is not None and self._thread.is_alive()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception as e:
                # the collector eating its own scrape failures is the
                # exact watchdog-blind-spot this repo lints against:
                # say it once, keep the loop alive
                _registry.warn_once(
                    "fleet.scrape_loop",
                    "paddle_tpu.monitor.fleet: scrape round failed "
                    "(collector still running): %r" % (e,))

    # -- one scrape round --------------------------------------------------

    def _resolve_endpoints(self):
        if self._store is not None and self.world_size:
            # re-discover ranks that never announced AND ranks whose
            # endpoint has gone dark: a restarted rank (the PR-7
            # elastic flow) re-announces on a FRESH ephemeral port, and
            # a collector that kept dialing the dead URL forever would
            # permanently lose that rank's coverage
            stale = {r for r, st in self._rank_items()
                     if st.get("consecutive_errors", 0) >= 2}
            missing = [r for r in range(self.world_size)
                       if r not in self._endpoints or r in stale]
            if missing:
                found = discover_endpoints(self._store, self.world_size)
                for r, rec in found.items():
                    if r in stale or r not in self._endpoints:
                        self._endpoints[r] = rec["url"]
        return dict(self._endpoints)

    def _scrape_rank(self, rank, url):
        """One rank's scrape: /metrics.json + /debugz/perf + /healthz,
        with the HTTP exchange doubling as the NTP-style clock probe
        (rank-reported unix_time vs the local request midpoint; the
        min-RTT sample wins, the PR-2 trace_merge discipline).
        ``scraped_at`` is a MONOTONIC stamp: every consumer subtracts
        it (freshness ages, progress windows) and a wall step must not
        fake or mask staleness."""
        snap, t0, t1, rtt = _http_json(url + "/metrics.json",
                                       self.http_timeout_s)
        offset = None
        if isinstance(snap.get("unix_time"), (int, float)):
            offset = float(snap["unix_time"]) - (t0 + t1) / 2.0
        perf, _, _, _ = _http_json(url + "/debugz/perf",
                                   self.http_timeout_s)
        healthz, _, _, _ = _http_json(url + "/healthz",
                                      self.http_timeout_s)
        # flight-recorder seq watermark (best-effort): the second skew
        # signal next to train_steps_total — which COLLECTIVE stream is
        # behind, not just which optimizer loop. Narrow catch: an
        # unreachable or non-JSON /debugz/flight simply leaves the
        # watermark None this round.
        flight_seq = None
        try:
            flight, _, _, _ = _http_json(url + "/debugz/flight",
                                         self.http_timeout_s)
            if isinstance(flight.get("next_seq"), (int, float)):
                flight_seq = int(flight["next_seq"])
        except (OSError, ValueError, http.client.HTTPException):
            pass
        # memory plane (best-effort, same narrow-catch contract): a
        # rank without the route or with FLAGS_monitor_memory off just
        # has empty memory columns this round
        memory = None
        try:
            mem, _, _, _ = _http_json(url + "/debugz/memory",
                                      self.http_timeout_s)
            if isinstance(mem, dict):
                memory = mem
        except (OSError, ValueError, http.client.HTTPException):
            pass
        # profiling plane (best-effort, same contract): sampler summary
        # + measured dispatch/blocked/gap per job — feeds the HOSTBLK%
        # column; absent or flags-off ranks just have an empty column
        profile = None
        try:
            prof, _, _, _ = _http_json(url + "/debugz/profile",
                                       self.http_timeout_s)
            if isinstance(prof, dict):
                profile = prof
        except (OSError, ValueError, http.client.HTTPException):
            pass
        # serving-fleet router (best-effort, same contract): a rank
        # hosting a router reports replica/affinity columns so ONE pane
        # shows the training fleet and the serving fleet; every other
        # rank (or a pre-router build) just has empty columns
        router = None
        try:
            rt, _, _, _ = _http_json(url + "/debugz/router",
                                     self.http_timeout_s)
            if isinstance(rt, dict) and rt.get("router"):
                router = rt["router"]
        except (OSError, ValueError, http.client.HTTPException):
            pass
        # SLO + incident planes (best-effort, same contract): the
        # rank's objective verdicts feed the SLO/BUDGET columns and
        # its incident table feeds the merged /debugz/fleet/incidents
        # timeline; a flags-off or pre-ptslo rank just has empty
        # columns this round
        slo = None
        try:
            sl, _, _, _ = _http_json(url + "/debugz/slo",
                                     self.http_timeout_s)
            if isinstance(sl, dict) and sl.get("enabled"):
                slo = sl
        except (OSError, ValueError, http.client.HTTPException):
            pass
        incidents = None
        try:
            inc, _, _, _ = _http_json(url + "/debugz/incidents",
                                      self.http_timeout_s)
            if isinstance(inc, dict) and inc.get("enabled"):
                incidents = inc
        except (OSError, ValueError, http.client.HTTPException):
            pass
        return {"metrics": snap.get("metrics") or {},
                "snapshot_time": snap.get("unix_time"),
                "perf": perf, "healthz": healthz,
                "flight_seq": flight_seq, "memory": memory,
                "profile": profile, "router": router,
                "slo": slo, "incidents": incidents,
                "rtt_s": rtt, "clock_offset_s": offset,
                "scraped_at": time.monotonic()}

    @staticmethod
    def _metric_value(mets, name, kind="sum"):
        """Scalar view of one rank's metric: sum (counters) or max
        (gauges with per-engine labels) across its series."""
        m = mets.get(name)
        if not m:
            return None
        vals = [s.get("value") for s in m.get("series", ())
                if isinstance(s.get("value"), (int, float))]
        if not vals:
            return None
        return sum(vals) if kind == "sum" else max(vals)

    @staticmethod
    def _hist_totals(mets, name):
        """(sum, count) across one rank's histogram series."""
        m = mets.get(name)
        if not m:
            return None
        tot_s, tot_c = 0.0, 0
        for s in m.get("series", ()):
            tot_s += float(s.get("sum", 0.0) or 0.0)
            tot_c += int(s.get("count", 0) or 0)
        return tot_s, tot_c

    def _derive_rank_row(self, rank, st, scraped):
        """Update rank ``st`` with the derived table fields from a
        fresh ``scraped`` payload (step-time window estimate, mfu,
        comm share, heartbeat age, anomaly watermark)."""
        mets = scraped["metrics"]
        now = scraped["scraped_at"]
        prev_sum_count = st.get("_step_hist")
        hist = self._hist_totals(mets, "train_step_seconds")
        step_time = st.get("step_time_s")
        if hist is not None:
            st["_step_hist"] = hist
            if prev_sum_count is not None:
                d_sum = hist[0] - prev_sum_count[0]
                d_count = hist[1] - prev_sum_count[1]
                if d_count > 0:
                    step_time = d_sum / d_count
                    st["last_progress_at"] = now
                elif st.get("last_progress_at") is not None:
                    # no step completed this window: the rank is AT
                    # LEAST this slow — let the estimate grow so a
                    # fully wedged rank trends toward straggler/stall
                    # instead of freezing at its last healthy number
                    stuck = now - st["last_progress_at"]
                    step_time = max(step_time or 0.0, stuck)
            elif hist[1] > 0:
                step_time = hist[0] / hist[1]
                st["last_progress_at"] = now
        st["step_time_s"] = step_time
        st["steps_total"] = self._metric_value(
            mets, "train_steps_total")
        st["tokens_per_s"] = self._metric_value(
            mets, "train_tokens_per_s", kind="max")
        # perf payload: headline efficiency numbers per job
        jobs = (scraped["perf"] or {}).get("jobs") or {}
        mfu = [j.get("mfu") for j in jobs.values()
               if isinstance(j.get("mfu"), (int, float))]
        st["mfu"] = max(mfu) if mfu else None
        hbm = [j.get("hbm_peak_bytes") for j in jobs.values()
               if isinstance(j.get("hbm_peak_bytes"), (int, float))]
        st["hbm_peak_bytes"] = max(hbm) if hbm else None
        comm = [j.get("phase_share", {}).get("comm")
                for j in jobs.values()
                if isinstance(j.get("phase_share", {}).get("comm"),
                              (int, float))]
        st["comm_share"] = max(comm) if comm else None
        goodput = [j.get("serving_goodput_tokens_per_s")
                   for j in jobs.values()
                   if isinstance(j.get("serving_goodput_tokens_per_s"),
                                 (int, float))]
        if goodput:
            st["serving_goodput_tokens_per_s"] = max(goodput)
        # healthz: status + freshest heartbeat age
        hz = scraped["healthz"] or {}
        st["healthz"] = hz.get("status")
        st["degraded"] = bool(hz.get("degraded"))
        ages = [h.get("last_beat_age_s")
                for h in (hz.get("heartbeats") or {}).values()
                if isinstance(h.get("last_beat_age_s"), (int, float))]
        st["heartbeat_age_s"] = min(ages) if ages else None
        st["collective_seq"] = scraped.get("flight_seq")
        # memory columns (monitor/memory.py /debugz/memory): live
        # bytes prefer the allocator witness, fall back to the ledger
        # total (bare workers never import jax, so the witness may be
        # absent while the ledger reports); headroom is the tightest
        # job's
        mem = scraped.get("memory") or {}
        rec = mem.get("reconciliation") or {}
        live = rec.get("live_bytes")
        if not isinstance(live, (int, float)):
            live = rec.get("ledger_bytes")
        st["mem_live_bytes"] = live if isinstance(live, (int, float)) \
            else None
        heads = [j.get("headroom_bytes")
                 for j in (mem.get("jobs") or {}).values()
                 if isinstance(j.get("headroom_bytes"), (int, float))]
        st["mem_headroom_bytes"] = min(heads) if heads else None
        # profiling column (monitor/profile.py): host-blocked share of
        # the LAST measured step window — from the per-step gauges
        # mirrored into the perf job rows, not the lifetime totals (a
        # rank that blocked an hour ago but recovered must not wear a
        # red HOSTBLK% forever). Worst job wins, the memory columns'
        # convention.
        shares = []
        for j in jobs.values():
            d = j.get("profile_dispatch_seconds")
            b = j.get("profile_host_blocked_seconds")
            g = j.get("profile_host_gap_seconds")
            if all(isinstance(x, (int, float)) for x in (d, b, g)) \
                    and (d + b + g) > 0:
                shares.append(b / (d + b + g))
        st["profile_host_blocked_share"] = max(shares) if shares \
            else None
        # the /debugz/profile summary scrape: where the rank's host
        # time goes by the sampler's attribution (dominant component)
        prof = scraped.get("profile") or {}
        comps = prof.get("components") or {}
        st["profile_top_component"] = max(
            comps, key=lambda c: comps[c].get("share", 0)) if comps \
            else None
        # serving-fleet router columns (/debugz/router, best-effort):
        # live replica count + affinity hit rate for a rank hosting a
        # router — None everywhere else (the fleet_top REPLICAS /
        # AFFIN% columns)
        rt = scraped.get("router") or {}
        reps = rt.get("replicas") or {}
        st["router_replicas"] = reps.get("live") \
            if isinstance(reps.get("live"), int) else None
        aff = rt.get("affinity") or {}
        st["router_affinity_hit_rate"] = aff.get("hit_rate") \
            if isinstance(aff.get("hit_rate"), (int, float)) else None
        # SLO columns (/debugz/slo, best-effort): the rank's WORST
        # objective — min attainment and min budget remaining across
        # its judged objectives (the memory columns' worst-wins
        # convention); None for flags-off or pre-ptslo ranks
        slo = scraped.get("slo") or {}
        atts = [o.get("attainment")
                for o in (slo.get("objectives") or ())
                if isinstance(o.get("attainment"), (int, float))]
        st["slo_attainment_min"] = min(atts) if atts else None
        buds = [o.get("budget_remaining_ratio")
                for o in (slo.get("objectives") or ())
                if isinstance(o.get("budget_remaining_ratio"),
                              (int, float))]
        st["slo_budget_min"] = min(buds) if buds else None
        # incident columns + the raw table (the /debugz/fleet/incidents
        # merge reads the latest scraped table per rank)
        incidents = scraped.get("incidents")
        st["incidents_open"] = (
            len(incidents.get("open") or ())
            if isinstance(incidents, dict) else None)
        st["_incidents"] = incidents
        # anomaly watermark: total sentinel firings this rank reports
        anomalies = (scraped["perf"] or {}).get("anomalies") or {}
        st["anomalies_total"] = sum(
            (anomalies.get("counts") or {}).values())
        st["anomaly_kinds"] = sorted((anomalies.get("counts") or {}))

    def _fetch_all(self, endpoints):
        """HTTP-fetch every rank concurrently: a dead rank costs its
        own connect timeout, not a serial stall of the whole round (2
        unreachable ranks at a 3 s timeout must not turn a 2 s scrape
        interval into an 8 s one — detection latency is the product).
        Returns {rank: scraped dict | Exception}. State mutation stays
        on the caller (collector) thread."""
        if len(endpoints) <= 1:
            out = {}
            for rank, url in endpoints.items():
                try:
                    out[rank] = self._scrape_rank(rank, url)
                except Exception as e:
                    out[rank] = e
            return out
        if self._pool is None:
            import concurrent.futures

            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=min(16, max(len(endpoints), 2)),
                thread_name_prefix="pt-fleet-scrape")
        futures = {r: self._pool.submit(self._scrape_rank, r, u)
                   for r, u in endpoints.items()}
        out = {}
        for rank, fut in futures.items():
            try:
                out[rank] = fut.result(timeout=4 * self.http_timeout_s)
            except Exception as e:
                out[rank] = e
        return out

    def scrape_once(self):
        """One collector round: scrape every known endpoint, fuse, run
        straggler detection, maybe trigger a fleet capture. Returns the
        fused metric dict. Never raises (per-rank errors are recorded
        on the rank's row)."""
        endpoints = self._resolve_endpoints()
        fetched = self._fetch_all(endpoints)
        scraped_by_rank = {}
        for rank, url in sorted(endpoints.items()):
            # row INSERTION under the lock: route handlers iterate
            # _ranks concurrently, and a mid-iteration resize would
            # 500 the fleet view exactly during fleet bring-up (field
            # updates on an existing row dict are fine unlocked)
            with self._lock:
                st = self._ranks.setdefault(rank, {"rank": rank})
            st["url"] = url
            scraped = fetched.get(rank)
            if isinstance(scraped, Exception) or scraped is None:
                st["ok"] = False
                st["error"] = repr(scraped)
                st["consecutive_errors"] = \
                    st.get("consecutive_errors", 0) + 1
                _SCRAPE_ERRS.labels(rank=rank).inc()
                continue
            st["ok"] = True
            st["error"] = None
            st["consecutive_errors"] = 0
            st["scraped_at"] = scraped["scraped_at"]
            # min-RTT clock sample wins (NTP discipline): a slow scrape
            # mid-GC must not wobble an already-good offset estimate
            if scraped["clock_offset_s"] is not None and (
                    st.get("rtt_s") is None
                    or scraped["rtt_s"] <= st["rtt_s"]):
                st["rtt_s"] = scraped["rtt_s"]
                st["clock_offset_s"] = scraped["clock_offset_s"]
            self._derive_rank_row(rank, st, scraped)
            scraped_by_rank[rank] = scraped
        fused = fuse_snapshots(
            {r: s["metrics"] for r, s in scraped_by_rank.items()})
        anomaly_ranks = self._advance_anomaly_watermarks(scraped_by_rank)
        with self._lock:
            if scraped_by_rank:
                self._fused = fused
            # else: keep the last good fused view — a transient
            # full-fleet outage is visible on the per-rank rows
            # (ok=False + consecutive_errors), not by silently
            # blanking every aggregate
            self._scrapes += 1
            self._last_scrape_at = time.time()
        _SCRAPES.inc()
        _RANKS_OK.set(len(scraped_by_rank))
        new_stragglers = self._detect_stragglers()
        if anomaly_ranks:
            self._maybe_capture(
                "anomaly", {"ranks": sorted(anomaly_ranks)})
        if new_stragglers:
            self._maybe_capture(
                "straggler", {"ranks": sorted(new_stragglers)})
            # ptprof (monitor/profile.py): a fresh straggler also arms
            # a local device-capture window — the per-rank folded
            # stacks ride the fleet capture's /debugz/profile pulls,
            # this adds the collector rank's own Xprof window. No-op
            # while FLAGS_monitor_profile is off.
            try:
                from . import profile as _profile

                _profile.on_straggler(sorted(new_stragglers))
            except Exception as e:
                _registry.warn_once(
                    "fleet.profile_arm",
                    "paddle_tpu.monitor.fleet: profile capture arming "
                    "failed (straggler was still flagged): %r" % (e,))
        # flush triggers the cooldown deferred: their watermarks have
        # already advanced and will not re-fire on their own
        self._maybe_capture()
        return fused

    def _advance_anomaly_watermarks(self, scraped_by_rank):
        """Ranks whose sentinel firing count advanced (or that turned
        degraded) since the previous round — the capture trigger."""
        fired = set()
        for rank, scraped in scraped_by_rank.items():
            st = self._ranks[rank]
            total = st.get("anomalies_total") or 0
            mark = st.get("_anomaly_mark")
            degraded = st.get("degraded", False)
            was_degraded = st.get("_was_degraded", False)
            if mark is not None and total > mark:
                fired.add(rank)
            elif degraded and not was_degraded:
                fired.add(rank)
            st["_anomaly_mark"] = total
            st["_was_degraded"] = degraded
        return fired

    # -- straggler detection -----------------------------------------------

    def _detect_stragglers(self):
        """Cross-rank step-time comparison: flag ranks persistently
        slower than ``straggler_factor`` x the fleet median. Returns
        the set of NEWLY flagged ranks (an episode fires once; a rank
        that recovers clears its episode and can re-fire)."""
        rows = {r: st for r, st in self._ranks.items()
                if st.get("ok") and isinstance(st.get("step_time_s"),
                                               (int, float))}
        newly = set()
        if len(rows) >= 2:
            times = sorted(st["step_time_s"] for st in rows.values())
            # LOWER median on even fleets: in a 2-rank world the upper
            # median IS the slow rank's own time (nothing could ever be
            # flagged); the lower median compares each rank against the
            # healthy half's pace
            median = times[(len(times) - 1) // 2]
            steps = [st.get("steps_total") for st in rows.values()
                     if isinstance(st.get("steps_total"), (int, float))]
            front = max(steps) if steps else None
            seqs = [st.get("collective_seq") for st in rows.values()
                    if isinstance(st.get("collective_seq"), int)]
            front_seq = max(seqs) if seqs else None
            for r, st in rows.items():
                if front is not None and \
                        isinstance(st.get("steps_total"), (int, float)):
                    st["steps_behind"] = max(
                        int(front - st["steps_total"]), 0)
                if front_seq is not None and \
                        isinstance(st.get("collective_seq"), int):
                    st["collective_seq_behind"] = \
                        front_seq - st["collective_seq"]
                slow = median > 0 and \
                    st["step_time_s"] > self.straggler_factor * median
                if slow:
                    st["slow_hits"] = st.get("slow_hits", 0) + 1
                else:
                    st["slow_hits"] = 0
                    if r in self._stragglers:
                        # recovered: close the episode so a relapse
                        # counts as a fresh straggler_total increment
                        # — and resolve its incident (the table lives
                        # on the collector, which detected it; no-op
                        # branch while the SLO plane is off)
                        self._stragglers.pop(r, None)
                        st["straggler"] = False
                        try:
                            from . import incidents as _incidents

                            _incidents.resolve(
                                "fleet/straggler/rank%d" % r,
                                reason="step time recovered to fleet "
                                "pace")
                        except Exception as e:
                            _registry.warn_once(
                                "fleet.incident_resolve",
                                "paddle_tpu.monitor.fleet: straggler "
                                "incident resolve failed (episode "
                                "still closed): %r" % (e,))
                if st.get("slow_hits", 0) >= self.straggler_persist \
                        and r not in self._stragglers:
                    info = {
                        "rank": r,
                        "step_time_s": st["step_time_s"],
                        "fleet_median_s": median,
                        "factor": self.straggler_factor,
                        "flagged_at": time.time(),
                        "steps_behind": st.get("steps_behind"),
                    }
                    with self._lock:
                        self._stragglers[r] = info
                    st["straggler"] = True
                    newly.add(r)
                    _STRAGGLER_TOTAL.labels(rank=r).inc()
                    # ptslo: ONE incident per straggler episode,
                    # naming the guilty rank; the recovery branch
                    # above resolves it, a relapse opens a fresh one
                    try:
                        from . import incidents as _incidents

                        _incidents.open(
                            "fleet/straggler/rank%d" % r,
                            severity="ticket", kind="straggler",
                            source="fleet", rank=r,
                            summary="rank %d straggling: step %.3fs "
                            "vs fleet median %.3fs" % (
                                r, st["step_time_s"], median),
                            evidence=dict(info))
                    except Exception as e:
                        _registry.warn_once(
                            "fleet.incident_open",
                            "paddle_tpu.monitor.fleet: straggler "
                            "incident open failed (episode still "
                            "flagged): %r" % (e,))
        return newly

    # -- anomaly-triggered fleet capture -------------------------------------

    def _maybe_capture(self, reason=None, detail=None):
        """Capture-with-cooldown. A trigger arriving inside the
        cooldown is QUEUED, never dropped (its watermark has already
        advanced and will not re-fire); the next eligible round fires
        one capture for the oldest pending trigger, with any later
        ones folded into its detail under ``also`` — distinct
        incidents keep their reason/detail attribution in the
        manifest. ``reason=None`` = flush-pending only. The cooldown
        interval is measured on the monotonic clock — an NTP step must
        neither extend nor collapse it."""
        now = time.monotonic()
        if reason is not None:
            self._pending_captures.append((reason, detail or {}))
        if not self._pending_captures:
            return None
        if self._last_capture_at is not None and \
                now - self._last_capture_at < self.capture_cooldown_s:
            return None
        if len(self._captures) >= self.max_captures:
            self._pending_captures = []
            return None
        pending, self._pending_captures = self._pending_captures, []
        reason, detail = pending[0]
        if len(pending) > 1:
            detail = dict(detail)
            detail["also"] = [{"reason": r, "detail": d}
                              for r, d in pending[1:]]
        self._last_capture_at = now
        try:
            return self.capture(reason, detail)
        except Exception as e:
            _registry.warn_once(
                "fleet.capture",
                "paddle_tpu.monitor.fleet: anomaly capture failed "
                "(trigger %r consumed, no capture dir written): %r"
                % (reason, e))
            return None

    def capture(self, reason="manual", detail=None):
        """Pull watchdog-style bundles + trace-journal tails from every
        reachable rank into one ``fleet_capture_<ts>/`` directory.
        Returns the capture dir path."""
        ts = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        d = os.path.join(self.capture_dir, "fleet_capture_%s" % ts)
        n = 1
        while os.path.exists(d):
            d = os.path.join(self.capture_dir,
                             "fleet_capture_%s_%d" % (ts, n))
            n += 1
        os.makedirs(d, exist_ok=True)
        # resolve ONCE: discovery does blocking store reads for absent
        # ranks (the normal state mid-incident), and the pull loop and
        # manifest must agree on the endpoint set
        endpoints = self._resolve_endpoints()
        got_ranks = []
        for rank, url in sorted(endpoints.items()):
            ok = True
            for route, stem in (("debugz/bundle", "bundle"),
                                ("debugz/trace/journal", "journal"),
                                ("debugz/memory", "memory"),
                                ("debugz/profile", "profile")):
                try:
                    payload, _, _, _ = _http_json(
                        "%s/%s" % (url, route), self.http_timeout_s)
                except Exception as e:
                    payload = {"error": repr(e), "rank": rank,
                               "route": route}
                    ok = False
                path = os.path.join(d, "%s_rank%d.json" % (stem, rank))
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(payload, f, indent=1, default=str)
                    f.write("\n")
                os.replace(tmp, path)
            if ok:
                got_ranks.append(rank)
        # the serving-fleet router (when one runs in THIS process —
        # the tools/serving_router.py shape) journals the dispatch
        # half of every fleet trace: write its journal locally so the
        # capture carries router+replica fragments of one incident
        router_journal = None
        if _sfleet_enabled() and _router_hook is not None:
            from . import trace as _trace
            if _trace.is_enabled():
                path = os.path.join(d, "journal_router.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(_trace.dump(), f, indent=1, default=str)
                    f.write("\n")
                os.replace(tmp, path)
                router_journal = "journal_router.json"
        manifest = {
            "kind": "fleet_capture",
            "version": 1,
            "router_journal": router_journal,
            "reason": reason,
            "detail": detail or {},
            "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime()),
            "unix_time": time.time(),
            "world_size": self.world_size,
            "ranks": got_ranks,
            "endpoints": {str(r): u for r, u in
                          sorted(endpoints.items())},
            "clock_offsets_s": {
                str(r): st.get("clock_offset_s")
                for r, st in self._rank_items()
                if st.get("clock_offset_s") is not None},
            "stragglers": {str(r): i for r, i in
                           sorted(self._stragglers.items())},
            # causality: the open incidents known fleet-wide when the
            # capture fired — the triggering incident's id is in here
            # (its detector opened it before the watermark advanced).
            # Empty while FLAGS_monitor_slo is off everywhere.
            "incidents": self._known_open_incident_ids(),
        }
        tmp = os.path.join(d, "manifest.json.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, default=str)
            f.write("\n")
        os.replace(tmp, os.path.join(d, "manifest.json"))
        rec = {"dir": d, "reason": reason, "detail": detail or {},
               "created_at": manifest["unix_time"],
               "ranks": got_ranks,
               "incidents": manifest["incidents"]}
        with self._lock:
            self._captures.append(rec)
        _CAPTURES_TOTAL.labels(reason=reason).inc()
        # back-link: the collector's OWN open incidents (stragglers,
        # local detectors) get the capture dir as evidence — remote
        # incidents get the link at merge time via the manifest ids
        try:
            from . import incidents as _incidents

            for inc in _incidents.open_incidents():
                _incidents.add_evidence(inc["key"], capture_dir=d)
        except Exception as e:
            _registry.warn_once(
                "fleet.capture_evidence",
                "paddle_tpu.monitor.fleet: capture evidence back-link "
                "failed (capture %s still written): %r" % (d, e))
        return d

    def _known_open_incident_ids(self):
        """Open incident ids across the collector's own table and the
        latest scraped table of every rank (deduped — the collector's
        process may also be a scraped rank)."""
        ids = []
        try:
            from . import incidents as _incidents

            for inc in _incidents.open_incidents():
                ids.append(inc["id"])
        except Exception as e:
            _registry.warn_once(
                "fleet.incident_ids",
                "paddle_tpu.monitor.fleet: local incident-id walk "
                "failed (scraped ids still recorded): %r" % (e,))
        for _, st in self._rank_items():
            pay = st.get("_incidents")
            if isinstance(pay, dict):
                for inc in pay.get("open") or ():
                    if inc.get("id"):
                        ids.append(inc["id"])
        return sorted(set(ids))

    # -- payloads ------------------------------------------------------------

    def _rank_items(self):
        """Sorted (rank, row) pairs, snapshotted under the lock (rows
        are inserted by the collector thread while route handlers
        read)."""
        with self._lock:
            return sorted(self._ranks.items())

    def ranks_table(self):
        """Per-rank table rows (the /debugz/fleet/ranks body and the
        fleet_top columns), sorted by rank. Freshness ages subtract
        monotonic stamps (``scraped_at`` is monotonic)."""
        now = time.monotonic()
        rows = []
        for r, st in self._rank_items():
            rows.append({k: st.get(k) for k in (
                "rank", "url", "ok", "error", "consecutive_errors",
                "steps_total", "steps_behind", "collective_seq",
                "collective_seq_behind", "step_time_s",
                "tokens_per_s", "mfu", "hbm_peak_bytes",
                "mem_live_bytes", "mem_headroom_bytes",
                "profile_host_blocked_share", "profile_top_component",
                "comm_share",
                "serving_goodput_tokens_per_s", "heartbeat_age_s",
                "healthz", "degraded", "anomalies_total",
                "anomaly_kinds", "straggler", "slow_hits",
                "router_replicas", "router_affinity_hit_rate",
                "slo_attainment_min", "slo_budget_min",
                "incidents_open",
                "clock_offset_s", "rtt_s")})
            rows[-1]["scrape_age_s"] = (
                round(now - st["scraped_at"], 3)
                if st.get("scraped_at") else None)
        return rows

    def summary(self):
        """The /debugz/fleet body: collector state, straggler verdict,
        fleet aggregates (per-rank series live at /debugz/fleet/ranks
        and /metrics/fleet)."""
        with self._lock:
            fused = self._fused
            stragglers = dict(self._stragglers)
            captures = list(self._captures)
            scrapes = self._scrapes
            last = self._last_scrape_at
            rank_rows = list(self._ranks.items())
        aggregates = {}
        for name, ent in fused.items():
            aggregates[name] = {
                "kind": ent["kind"],
                "series": [{"labels": s["labels"], "fleet": s["fleet"]}
                           for s in ent["series"]],
            }
        ok = [r for r, st in rank_rows if st.get("ok")]
        return {
            "enabled": True,
            "collector": {
                "running": self.is_running(),
                "rank": self.rank,
                "interval_s": self.interval_s,
                "scrapes": scrapes,
                "started_at": self._started_at,
                "last_scrape_at": last,
            },
            "world_size": self.world_size,
            "ranks_known": sorted(r for r, _ in rank_rows),
            "ranks_ok": sorted(ok),
            "straggler_policy": {
                "factor": self.straggler_factor,
                "persist": self.straggler_persist,
                "signal": "train_step_seconds windowed mean vs fleet "
                          "median",
            },
            "stragglers": {str(r): i for r, i in
                           sorted(stragglers.items())},
            "captures": captures,
            "aggregates": aggregates,
            "time": time.time(),
        }

    def fused(self):
        with self._lock:
            return dict(self._fused)

    def prometheus_text(self):
        """Federation-style exposition of the fused fleet view: every
        scraped counter/gauge series re-exposed with a ``rank`` label,
        plus fleet aggregates (``_fleet_sum`` for counters,
        ``_fleet_min``/``_fleet_max``/``_fleet_p50`` for gauges,
        bucket-wise-summed ``_fleet`` histograms)."""
        with self._lock:
            fused = dict(self._fused)
        lines = []
        for name in sorted(fused):
            ent = fused[name]
            kind = ent["kind"]
            if kind == "histogram":
                lines.append("# TYPE %s_fleet histogram" % name)
                for se in ent["series"]:
                    lbl = dict(se["labels"])
                    fl = se["fleet"]
                    for b in sorted(fl.get("buckets", {}),
                                    key=lambda x: float(x)):
                        lines.append("%s %d" % (_series(
                            "%s_fleet_bucket" % name,
                            dict(lbl, le=b)), fl["buckets"][b]))
                    lines.append("%s %d" % (_series(
                        "%s_fleet_bucket" % name,
                        dict(lbl, le="+Inf")), fl.get("count", 0)))
                    lines.append("%s %s" % (_series(
                        "%s_fleet_sum" % name, lbl),
                        _registry._fmt(fl.get("sum", 0.0))))
                    lines.append("%s %d" % (_series(
                        "%s_fleet_count" % name, lbl),
                        fl.get("count", 0)))
                continue
            lines.append("# TYPE %s %s" % (name, kind))
            for se in ent["series"]:
                # a scraped series that ALREADY carries a rank label
                # (fleet_straggler_total{rank}, fleet_rank_info) keeps
                # it — clobbering would misattribute it to the scraped
                # rank and collapse distinct series into duplicate
                # exposition lines; the scrape origin rides a separate
                # label instead
                origin = "scraped_rank" if "rank" in se["labels"] \
                    else "rank"
                for rank in sorted(se["per_rank"]):
                    lines.append("%s %s" % (_series(
                        name, dict(se["labels"], **{origin: rank})),
                        _registry._fmt(se["per_rank"][rank])))
            if kind == "counter":
                lines.append("# TYPE %s_fleet_sum counter" % name)
                for se in ent["series"]:
                    if "sum" in se["fleet"]:
                        lines.append("%s %s" % (_series(
                            "%s_fleet_sum" % name, se["labels"]),
                            _registry._fmt(se["fleet"]["sum"])))
            elif kind == "gauge":
                for stat in ("min", "max", "p50"):
                    lines.append("# TYPE %s_fleet_%s gauge"
                                 % (name, stat))
                    for se in ent["series"]:
                        if stat in se["fleet"]:
                            lines.append("%s %s" % (_series(
                                "%s_fleet_%s" % (name, stat),
                                se["labels"]),
                                _registry._fmt(se["fleet"][stat])))
        return "\n".join(lines) + "\n"


def _series(name, labels):
    if not labels:
        return name
    keys = sorted(labels)
    return _registry._series(name, keys, [labels[k] for k in keys])


# -- process-wide collector + route payloads ---------------------------------

_collector = None


def get_collector():
    return _collector


def start_collector(**kw):
    """Start (or return) the process-wide collector thread."""
    global _collector
    if _collector is None or not _collector.is_running():
        _collector = FleetCollector(**kw).start()
    return _collector


def stop_collector(snapshot_out=None):
    global _collector
    if _collector is not None:
        _collector.stop(snapshot_out=snapshot_out)
        _collector = None


def fleet_payload():
    """The /debugz/fleet body (route-pinned 200 whether or not a
    collector runs here: "off/elsewhere" is a payload, not an error)."""
    c = _collector
    if c is None:
        return {"enabled": is_enabled(), "collector": None,
                "announced_url": _announce.url, "time": time.time()}
    out = c.summary()
    out["enabled"] = is_enabled()
    out["announced_url"] = _announce.url
    return out


def ranks_payload():
    """The /debugz/fleet/ranks body."""
    c = _collector
    if c is None:
        return {"enabled": is_enabled(), "collector": None,
                "ranks": [], "time": time.time()}
    with c._lock:
        stragglers = sorted(c._stragglers)
        scrapes = c._scrapes
    return {"enabled": is_enabled(),
            "collector": {"running": c.is_running(),
                          "scrapes": scrapes},
            "world_size": c.world_size,
            "stragglers": stragglers,
            "ranks": c.ranks_table(),
            "time": time.time()}


def fleet_incidents_payload():
    """The /debugz/fleet/incidents body: one clock-offset-aligned
    fleet-wide incident timeline — the collector's own table merged
    with the latest scraped table of every rank, deduped by incident
    id (ids embed (rank, pid), so the collector re-seeing its own
    rank's table, or re-scraping a rank, never duplicates an
    episode). Peer wall stamps are shifted onto the collector's clock
    by the per-rank NTP-style offsets (the trace_merge discipline);
    capture manifests' incident ids back-link each merged incident to
    its capture dir."""
    from . import incidents as _incidents

    if not _incidents.is_enabled():
        return {"enabled": False, "incidents": []}
    merged = {}
    local = _incidents.payload()
    for inc in (local.get("open") or []) + \
            (local.get("resolved") or []):
        e = dict(inc)
        e["evidence"] = dict(e.get("evidence") or {})
        e["origin"] = "local"
        e["origin_rank"] = e.get("rank")
        merged[e["id"]] = e
    c = _collector
    ranks_merged = []
    if c is not None:
        for r, st in c._rank_items():
            pay = st.get("_incidents")
            if not isinstance(pay, dict):
                continue
            ranks_merged.append(r)
            offset = st.get("clock_offset_s") or 0.0
            for inc in (pay.get("open") or []) + \
                    (pay.get("resolved") or []):
                if not inc.get("id"):
                    continue
                prev = merged.get(inc["id"])
                if prev is not None and prev.get("origin") == "local":
                    continue    # our own table is fresher than a scrape
                e = dict(inc)
                e["evidence"] = dict(e.get("evidence") or {})
                e["origin"] = "rank%d" % r
                e["origin_rank"] = r
                # align the peer's wall stamps onto the collector's
                # clock (display metadata only — never subtracted)
                for k in ("opened_at", "last_seen", "resolved_at"):
                    if isinstance(e.get(k), (int, float)):
                        e[k] = e[k] - offset
                merged[e["id"]] = e
        with c._lock:
            captures = list(c._captures)
        for cap in captures:
            for iid in cap.get("incidents") or ():
                if iid in merged:
                    merged[iid]["evidence"].setdefault(
                        "capture_dir", cap["dir"])
    timeline = sorted(merged.values(),
                      key=lambda e: (e.get("opened_at") or 0,
                                     e["id"]))
    open_n = sum(1 for e in timeline if e.get("state") == "open")
    return {
        "enabled": True,
        "collector": c is not None,
        "ranks_merged": ranks_merged,
        "counts": {"total": len(timeline), "open": open_n,
                   "resolved": len(timeline) - open_n},
        "incidents": timeline,
        "time": time.time(),
    }


def prometheus_fleet_text():
    """The /metrics/fleet exposition body."""
    c = _collector
    if c is None:
        return ("# fleet collector not running on this rank "
                "(FLAGS_monitor_fleet=%s)\n" % ("on" if is_enabled()
                                                else "off"))
    return c.prometheus_text()


# -- serving-fleet router hook (the /debugz/router routes) -------------------
#
# serving/fleet/router.py sets this slot when a Router starts on this
# process; the monitor plane never imports the serving package (the
# hook is duck-typed: any object with debug_payload() /
# replicas_debug_payload()). With FLAGS_serving_fleet off the slot
# stays None and the routes report the pinned disabled body —
# no serving import, no store traffic (test-pinned).

_router_hook = None


def set_router_hook(router):
    global _router_hook
    _router_hook = router


def clear_router_hook():
    global _router_hook
    _router_hook = None


def _sfleet_enabled():
    return _flag("FLAGS_serving_fleet")


def router_payload():
    """The /debugz/router body."""
    if not _sfleet_enabled():
        return {"enabled": False, "router": None}
    r = _router_hook
    if r is None:
        return {"enabled": True, "router": None,
                "time": time.time()}
    return {"enabled": True, "router": r.debug_payload(),
            "time": time.time()}


def router_replicas_payload():
    """The /debugz/router/replicas body."""
    if not _sfleet_enabled():
        return {"enabled": False, "replicas": []}
    r = _router_hook
    if r is None:
        return {"enabled": True, "replicas": [],
                "time": time.time()}
    return {"enabled": True, "replicas": r.replicas_debug_payload(),
            "time": time.time()}


def router_trace_federation(trace_id):
    """The ``federation`` block a router process's ``/debugz/trace/
    {id}`` attaches: the replica-side fragments of one fleet trace,
    fetched on demand through the hook. ``{"enabled": False}`` — and
    ZERO cross-replica fetches — whenever FLAGS_serving_fleet is off
    or no router runs here (test-pinned)."""
    if not _sfleet_enabled() or _router_hook is None:
        return {"enabled": False}
    segments = getattr(_router_hook, "trace_segments", None)
    if segments is None:
        return {"enabled": True, "segments": {}}
    return dict(segments(trace_id), enabled=True)


# -- fleet snapshot artifact (bench.py staleness discipline) ------------------

def snapshot_dict(collector=None):
    """JSON-ready fleet snapshot: the per-rank table + aggregates the
    tunnel-battery fleet row commits as ``tools/fleet_snapshot.json``."""
    c = collector or _collector
    if c is None:
        return {"kind": "fleet_snapshot", "version": 1, "ok": False,
                "error": "no collector"}
    summary = c.summary()
    return {
        "kind": "fleet_snapshot",
        "version": 1,
        # ok = real fused data exists (a run that ENDED before the
        # final scrape still has its last good rounds; per-rank ok
        # flags on the rows carry the momentary reachability)
        "ok": bool(summary["aggregates"]),
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "unix_time": time.time(),
        "pid": os.getpid(),
        "world_size": summary["world_size"],
        "scrapes": summary["collector"]["scrapes"],
        "ranks_ok": summary["ranks_ok"],
        "ranks": c.ranks_table(),
        "stragglers": summary["stragglers"],
        "straggler_policy": summary["straggler_policy"],
        "captures": summary["captures"],
        "aggregates": summary["aggregates"],
    }


def write_snapshot_artifact(path, collector=None, stale_reason=None):
    """Write the fleet snapshot artifact, with bench.py's staleness
    discipline: when this round produced NOTHING scrapeable (or the
    caller says so via ``stale_reason``) and a previous artifact
    exists, RE-EMIT it marked ``stale: true`` with
    ``stale_generations``/``stale_since`` — a photocopied fleet table
    must confess from the artifact itself. Returns the dict written."""
    snap = snapshot_dict(collector)
    if stale_reason is None and not snap.get("ok"):
        stale_reason = snap.get("error") or "no rank answered the scrape"
    if stale_reason is not None and os.path.exists(path):
        try:
            with open(path) as f:
                last = json.load(f)
        except (OSError, ValueError):
            last = None
        if last and last.get("kind") == "fleet_snapshot":
            last["stale"] = True
            last["stale_reason"] = stale_reason
            last["stale_generations"] = \
                int(last.get("stale_generations", 0)) + 1
            last.setdefault("stale_since",
                            last.get("written_at"))
            snap = last
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return snap
