"""Multi-rank chrome-trace aggregation: clock sync + aligned merge.

Per-rank profiler output (profiler.export_chrome_tracing /
export_merged_chrome_tracing) is one timeline per process with its own
host clock. To read comm/compute overlap across ranks — the exact
observability gap T3 names for fused distributed training — the per-rank
traces must land on ONE time base:

1. **Clock offset estimation** (``estimate_clock_offset``): an NTP-style
   ping exchange over the TCPStore. Rank 0 is the reference clock; each
   other rank sends its send-time, rank 0 echoes its own clock, and the
   requester takes the minimum-RTT sample's midpoint offset — accurate
   to ~RTT/2, far below the collective timescales being diagnosed.
   The exchange runs on ``time.monotonic()`` — the SAME timebase
   csrc/trace.cc stamps events with (steady_clock) — so the offset is
   directly the shift that aligns trace ``ts`` values; wall-clock skew
   would miss the per-host monotonic epoch (boot-time) delta entirely.
   ``write_clock_file`` persists the offset next to the trace so merging
   is an offline operation.

2. **Merge** (``merge_trace_files``): every rank's ``traceEvents`` are
   shifted by its offset (chrome ``ts`` is in microseconds) and its pids
   prefixed ``rank{r}/`` so process/thread tracks stay distinct in one
   Perfetto view. Metadata (``ph == "M"``) events ride along so track
   names survive.

The span-journal half (``merge_fleet_journals`` /
``write_fleet_timeline``) does the same for the serving fleet: the
router's journal plus each replica's, aligned by the collector-style
NTP wall-clock offsets and stitched with chrome flow arrows on the
traceparent linkage, so one Perfetto view shows a request's dispatch,
reroute and cross-replica finish under one trace id.

The CLI wrapper is tools/trace_merge.py.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import re
import time

CLOCK_FILE = "clock_rank%d.json"
_CLK_PREFIX = "__clk"


def estimate_clock_offset(store, rank, world_size, pings=8, prefix=None,
                          timeout_s=30):
    """Offset (seconds) such that t_rank0 ~= t_local + offset, in the
    MONOTONIC timebase the native tracer stamps events with — on
    distinct hosts this absorbs the boot-epoch delta wall clocks can't
    see, which is exactly the shift the merged trace needs.

    Collective over the store: EVERY rank must call this (rank 0 serves
    the echo side). Rank 0's offset is 0.0 by definition.
    """
    prefix = prefix or _CLK_PREFIX
    if rank == 0:
        for r in range(1, world_size):
            for i in range(pings):
                req_key = "%s/%d/req/%d" % (prefix, r, i)
                data = store.get(req_key, timeout_s=timeout_s)
                if data is None:
                    raise TimeoutError(
                        "clock sync: no ping %d from rank %d" % (i, r))
                store.set("%s/%d/rsp/%d" % (prefix, r, i),
                          repr(time.monotonic()).encode())
                # consume the request so a later sync round on the same
                # store starts from a clean exchange
                store.delete(req_key)
        return 0.0
    best_rtt, best_off = None, 0.0
    for i in range(pings):
        rsp_key = "%s/%d/rsp/%d" % (prefix, rank, i)
        t0 = time.monotonic()
        store.set("%s/%d/req/%d" % (prefix, rank, i),
                  repr(t0).encode())
        data = store.get(rsp_key, timeout_s=timeout_s)
        t2 = time.monotonic()
        if data is None:
            raise TimeoutError("clock sync: rank 0 did not echo ping %d"
                               % i)
        # delete the response immediately: a second sync round reusing
        # these key names must never read THIS round's echo (a stale
        # rsp reads as a near-zero RTT and wins min-RTT selection with
        # a garbage offset)
        store.delete(rsp_key)
        t1 = float(data.decode())
        rtt = t2 - t0
        if best_rtt is None or rtt < best_rtt:
            best_rtt, best_off = rtt, t1 - (t0 + t2) / 2.0
    return best_off


def write_clock_file(dir_name, rank, offset_s, rtt_s=None):
    os.makedirs(dir_name, exist_ok=True)
    path = os.path.join(dir_name, CLOCK_FILE % rank)
    with open(path, "w") as f:
        json.dump({"rank": rank, "offset_s": offset_s,
                   "rtt_s": rtt_s,
                   "written_at": time.strftime(
                       "%Y-%m-%dT%H:%M:%SZ", time.gmtime())}, f)
        f.write("\n")
    return path


def load_clock_offsets(dir_name):
    """{rank: offset_s} from clock_rank*.json files in a directory."""
    offsets = {}
    for path in glob.glob(os.path.join(dir_name, "clock_rank*.json")):
        try:
            with open(path) as f:
                rec = json.load(f)
            offsets[int(rec["rank"])] = float(rec["offset_s"])
        except (OSError, ValueError, KeyError):
            continue
    return offsets


def _load_events(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, list):
        return data
    return data.get("traceEvents", [])


def rank_of_path(path):
    """Infer the rank from a filename like trace_rank3.json / worker_3.json
    (last integer before the extension wins)."""
    stem = os.path.basename(path)
    stem = re.sub(r"\.(json|gz)$", "", re.sub(r"\.gz$", "", stem))
    nums = re.findall(r"(\d+)", stem)
    return int(nums[-1]) if nums else None


def merge_rank_events(rank_events, offsets=None):
    """{rank: [event, ...]} -> one aligned event list.

    ``ts`` shifts by the rank's clock offset (us); pids become
    ``rank{r}/{pid}``."""
    offsets = offsets or {}
    merged = []
    for rank in sorted(rank_events):
        shift_us = offsets.get(rank, 0.0) * 1e6
        for ev in rank_events[rank]:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = ev["ts"] + shift_us
            if "pid" in ev:
                ev["pid"] = "rank%d/%s" % (rank, ev["pid"])
            else:
                ev["pid"] = "rank%d" % rank
            merged.append(ev)
    return merged


def merge_trace_files(paths_by_rank, out_path, offsets=None,
                      extra_events=None):
    """Merge per-rank chrome traces into one aligned timeline file.

    ``paths_by_rank``: {rank: path} (.json or .json.gz).
    ``extra_events``: already-converted chrome events appended as-is —
    the span-journal request/step spans (``journal_events``) ride into
    the same Perfetto view as the rank-prefixed profiler tracks.
    Returns the merged event count."""
    rank_events = {r: _load_events(p) for r, p in paths_by_rank.items()}
    merged = merge_rank_events(rank_events, offsets)
    if extra_events:
        merged.extend(extra_events)
    d = os.path.dirname(os.path.abspath(out_path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged,
                   "displayTimeUnit": "ms",
                   "metadata": {
                       "merged_ranks": sorted(rank_events),
                       "extra_events": len(extra_events or ()),
                       "clock_offsets_s": {str(r): v for r, v in
                                           (offsets or {}).items()},
                   }}, f)
    return len(merged)


# -- span-journal merge (monitor/trace.py artifacts) -------------------------

def load_journal(path):
    """Read a ``trace.write_journal`` artifact (.json or .json.gz)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        journal = json.load(f)
    if journal.get("kind") != "trace_journal":
        raise ValueError(
            "%s is not a trace journal (kind=%r) — expected the "
            "monitor.trace.write_journal format"
            % (path, journal.get("kind")))
    return journal


def journal_events(journal, clock="monotonic"):
    """Journal -> chrome events. ``clock="monotonic"`` shifts span
    timestamps (wall clock) by the journal's own wall<->monotonic
    anchor onto the native tracer's steady-clock timebase — the right
    default when merging with chrome traces from the same process;
    ``clock="wall"`` keeps raw wall stamps (journal-only merges)."""
    from . import trace as _trace

    return _trace.chrome_events_from_journal(journal, clock=clock)


# -- fleet-capture merge (monitor/fleet.py artifacts) -------------------------

def load_fleet_capture(dir_name):
    """(manifest, {rank: journal dict}) from a ``fleet_capture_<ts>/``
    directory (monitor/fleet.py FleetCollector.capture). Per-rank
    journals that failed to pull (the capture writes an error stub in
    their place) are skipped — absence of a rank's journal is visible
    in the returned dict, never a crash."""
    with open(os.path.join(dir_name, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest.get("kind") != "fleet_capture":
        raise ValueError(
            "%s is not a fleet capture (kind=%r) — expected the "
            "monitor.fleet FleetCollector.capture format"
            % (dir_name, manifest.get("kind")))
    journals = {}
    for path in sorted(glob.glob(os.path.join(dir_name,
                                              "journal_rank*.json"))):
        rank = rank_of_path(path)
        if rank is None:
            continue
        try:
            journals[rank] = load_journal(path)
        except (ValueError, OSError):
            continue
    return manifest, journals


def capture_events(dir_name, clock="wall"):
    """(manifest, chrome events) for every rank journal in a fleet
    capture: pids are rank-prefixed (``rank{r}/...``) and — the fleet
    analog of the clock files — each rank's WALL timestamps shift by
    the manifest's collector-estimated clock offset onto the
    collector's clock, so cross-host spans line up in one Perfetto
    view. ``clock`` defaults to "wall": per-process monotonic anchors
    are boot-relative and meaningless across hosts."""
    manifest, journals = load_fleet_capture(dir_name)
    offsets = {}
    for r, v in (manifest.get("clock_offsets_s") or {}).items():
        if isinstance(v, (int, float)):
            offsets[int(r)] = float(v)
    evs = []
    for rank in sorted(journals):
        # offset = rank_clock - collector_clock, so subtracting it
        # lands the rank's wall stamps on the collector's clock
        shift_us = -offsets.get(rank, 0.0) * 1e6
        for ev in journal_events(journals[rank], clock=clock):
            ev = dict(ev)
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] += shift_us
            ev["pid"] = "rank%d/%s" % (rank, ev.get("pid", "trace"))
            evs.append(ev)
    # a serving-fleet capture also carries the router's own journal
    # (the dispatch half of every fleet trace, written collector-local
    # — its clock IS the reference, no shift)
    rpath = os.path.join(dir_name, "journal_router.json")
    if manifest.get("router_journal") and os.path.exists(rpath):
        try:
            rj = load_journal(rpath)
        except (ValueError, OSError):
            rj = None
        if rj is not None:
            for ev in journal_events(rj, clock=clock):
                ev = dict(ev)
                ev["pid"] = "router/%s" % ev.get("pid", "trace")
                evs.append(ev)
    return manifest, evs


# -- fleet-trace merge (router + replica journals, ONE trace id) --------------

def merge_fleet_journals(router_journal, replica_journals, offsets=None,
                         clock="wall"):
    """Stitch a serving-fleet router journal and its replicas' journals
    into one clock-aligned chrome event list: router tracks are pid
    ``router/...``, replica ``rank r`` tracks ``replica{r}/...``, and
    each replica's WALL timestamps shift by ``offsets[rank]`` (the
    collector-style NTP estimate: replica clock minus router clock) so
    attempt 1 on a killed replica, the reroute span naming the reason,
    and attempt 2 on the survivor read left-to-right under ONE trace
    id. Chrome flow arrows (``ph "s"/"f"``) connect every router
    ``dispatch`` span to the replica request span that adopted it —
    matched on ``(trace_id, remote_parent == dispatch span_id)``, the
    traceparent linkage, never timestamps."""
    offsets = offsets or {}
    evs = []
    for ev in journal_events(router_journal, clock=clock):
        ev = dict(ev)
        ev["pid"] = "router/%s" % ev.get("pid", "trace")
        evs.append(ev)
    # (trace_id, span_id) -> router dispatch span, for flow stitches
    dispatch = {}
    for tid, tr in (router_journal.get("traces") or {}).items():
        for s in tr.get("spans") or ():
            if s.get("kind") == "dispatch":
                dispatch[(tid, s["span_id"])] = (s, tr.get("name"))
    for rank in sorted(replica_journals):
        journal = replica_journals[rank]
        shift_s = -float(offsets.get(rank, 0.0))
        for ev in journal_events(journal, clock=clock):
            ev = dict(ev)
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] += shift_s * 1e6
            ev["pid"] = "replica%d/%s" % (rank, ev.get("pid", "trace"))
            evs.append(ev)
        for tid, tr in (journal.get("traces") or {}).items():
            for s in tr.get("spans") or ():
                rp = s.get("remote_parent")
                src = dispatch.get((tid, rp)) if rp is not None else None
                if src is None:
                    continue
                span, src_name = src
                fid = "%s/%x/r%d" % (tid, rp, rank)
                evs.append({
                    "ph": "s", "id": fid, "cat": "traceparent",
                    "name": "dispatch",
                    "pid": "router/%s" % (src_name or "trace"),
                    "tid": tid, "ts": span["t_start"] * 1e6})
                evs.append({
                    "ph": "f", "bp": "e", "id": fid,
                    "cat": "traceparent", "name": "dispatch",
                    "pid": "replica%d/%s" % (rank,
                                             tr.get("name") or "trace"),
                    "tid": tid,
                    "ts": (s["t_start"] + shift_s) * 1e6})
    return evs


def fleet_trace_summary(router_journal):
    """Per-trace reroute-causality rows from the ROUTER journal alone
    (it survives replica kills): ordered dispatch attempts with their
    replica + outcome, and the reroute spans with their reason — the
    merged-timeline acceptance contract in table form."""
    out = {}
    for tid, tr in (router_journal.get("traces") or {}).items():
        dispatches, reroutes = [], []
        for s in tr.get("spans") or ():
            attrs = s.get("attrs") or {}
            if s.get("kind") == "dispatch":
                dispatches.append({
                    "replica": attrs.get("replica"),
                    "outcome": attrs.get("outcome"),
                    "attempt": attrs.get("attempt"),
                    "t_start": s["t_start"]})
            elif s.get("kind") == "reroute":
                reroutes.append({"reason": attrs.get("reason"),
                                 "from_rank": attrs.get("from_rank"),
                                 "t_start": s["t_start"]})
        if not dispatches and not reroutes:
            continue
        out[tid] = {
            "name": tr.get("name"),
            "nonce": (tr.get("attrs") or {}).get("nonce"),
            "dispatches": sorted(dispatches,
                                 key=lambda d: d["t_start"]),
            "reroutes": sorted(reroutes, key=lambda r: r["t_start"]),
        }
    return out


def write_fleet_timeline(path, router_journal, replica_journals,
                         offsets=None, meta=None):
    """Write the merged fleet timeline artifact (``kind:
    "fleet_trace"``): the aligned chrome events plus the per-trace
    causality summary, so the artifact answers "which replica was
    attempt 1 / why did it move / where did it finish" without a
    Perfetto load. Atomic write; returns the dict written."""
    evs = merge_fleet_journals(router_journal, replica_journals,
                               offsets=offsets)
    doc = {
        "kind": "fleet_trace",
        "version": 1,
        "traceEvents": evs,
        "displayTimeUnit": "ms",
        "requests": fleet_trace_summary(router_journal),
        "metadata": dict(
            meta or {},
            router_cid=router_journal.get("cid"),
            replica_ranks=sorted(replica_journals),
            clock_offsets_s={str(r): v for r, v in
                             (offsets or {}).items()}),
    }
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return doc
