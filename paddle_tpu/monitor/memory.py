"""ptmem — the live HBM/memory plane: ledger, OOM forensics, leak watch.

The sixth and final pillar of the monitor division of labor (flight
recorder = collectives, watchdog = stalls, perf = efficiency, trace =
journeys, fleet = cross-rank, **memory = bytes**). Until this module
the repo's only memory observability was a compile-time
``hbm_peak_bytes`` estimate (monitor/perf.py) and one unlabeled
allocator gauge (parallel/engine.py) — nothing could say WHOSE bytes
filled the device when the ROADMAP item-2 router needs per-replica
load or item-3 trades KV bytes for occupancy. Four pieces:

1. **Per-component device-memory ledger**: engines register named
   components (model params, optimizer slots, EF residuals, each
   serving engine's paged KV pools with prefix-cache/COW detail) whose
   providers report ACTUAL live bytes from array ``nbytes``.
   ``sample()`` publishes ``mem_device_bytes{component,job}`` gauges
   (feeding the PR-5 time-series ring), reconciles the ledger total
   against the allocator witness (device ``memory_stats()`` where the
   backend has one, the summed ``jax.live_arrays()`` nbytes on the CPU
   backend — tolerance documented at ``RECONCILE_TOLERANCE``), and
   derives ``mem_hbm_headroom_bytes{job}`` = device capacity − (static
   ledger + compiled transient peak) so static-vs-transient
   attribution is explicit. The transient peak comes from the SAME
   donation-aware ``executable_analysis`` number perf attribution and
   ``graph_report()`` publish (``compiled_peak``), never a second
   hand-rolled estimate.

2. **OOM forensics**: the hot paths (``Engine.step``,
   ``CompiledTrainStep.__call__``/``run_steps``) catch OOM-shaped
   failures (XLA RESOURCE_EXHAUSTED, and the deterministic ``mem.oom``
   fault-injection site so the path is CPU-testable) and call
   ``write_postmortem`` BEFORE re-raising:
   ``oom_postmortem_rank{r}.json`` carries the ledger breakdown, the
   top-K live arrays by bytes (shape/dtype/tag), the caller context
   (KV occupancy, slots) plus the recent admission/preempt decision
   ring, and the last-K ``mem_*`` time-series tails. The engine never
   tries to recover — allocator state after a real OOM is unknowable.

3. **Leak sentinel** (``MemLeakSentinel`` via ``perf.add_sentinel``):
   steady-state growth of live bytes across a full sample window fires
   ``perf_anomalies_total{kind="mem_leak"}`` and flips ``/healthz`` to
   degraded through the existing perf anomaly plumbing. Armed only
   after warmup; window span is measured on the MONOTONIC clock.

4. **Surfacing**: ``/debugz/memory`` (monitor/exporter.py), per-rank
   memory columns in the fleet table (monitor/fleet.py scrapes the
   route; tools/fleet_top.py renders MEM/HEADROOM), fleet captures
   pull the breakdown from every rank, and watchdog bundles embed the
   ``mem_*`` ring tails.

Discipline (the PR-2/5/6 contract, test-pinned): default OFF via
``FLAGS_monitor_memory``. Engines latch ``tracker()`` ONCE at
construction (the ptlint hot-path-latch convention) — while off the
hot paths pay one attribute load + branch: no threads, no native
calls, no registry series, no jax import. Even enabled, the allocator
witness only consults jax when the HOST PROCESS already imported it
(``sys.modules`` probe) — a bare collector/worker process scraping the
route never drags an accelerator backend in. Module import stays
stdlib-only; jax objects only ever arrive through providers.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

from . import perf as _perf
from . import registry as _registry
from . import timeseries as _timeseries
from .timeseries import _flag

# -- metrics (shared registry; series appear only once sampled) --------------

_MEM_DEV = _registry.gauge(
    "mem_device_bytes",
    "live device bytes per registered ledger component (component="
    "allocator, job=device is the allocator witness the ledger "
    "reconciles against)", labelnames=("component", "job"))
_MEM_HEADROOM = _registry.gauge(
    "mem_hbm_headroom_bytes",
    "device capacity minus (static ledger + compiled transient peak) "
    "per job — the number item-3 int8-KV work is scored on",
    labelnames=("job",))
_MEM_UNATTRIBUTED = _registry.gauge(
    "mem_unattributed_bytes",
    "allocator live bytes the ledger cannot attribute to a registered "
    "component (reconciliation residue; tolerance in BASELINE.md)",
    labelnames=("job",))
_OOM_TOTAL = _registry.counter(
    "mem_oom_postmortems_total",
    "OOM postmortems written by the forensics path",
    labelnames=("job",))

# documented reconciliation tolerance (BASELINE.md round 14): on the
# CPU backend the witness is jax.live_arrays() — compile caches,
# donated-buffer turnover and test-suite junk live next to the tracked
# arrays, so the ledger is expected within this fraction (+ slack) of
# the witness DELTA across engine construction, not byte-equal
RECONCILE_TOLERANCE = 0.25
_DECISIONS_CAP = 64
_POSTMORTEMS_CAP = 16
_TOP_K = 12

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "out of memory", "Allocation failure")


class _MemState:
    __slots__ = ("lock", "components", "decisions", "postmortems",
                 "transient", "sentinel")

    def __init__(self):
        self.lock = threading.Lock()
        self.components = {}    # (job, name) -> provider
        self.decisions = []     # bounded admission/preempt ring
        self.postmortems = []   # bounded written-postmortem records
        self.transient = {}     # job -> {"bytes", "source"}
        self.sentinel = None


_state = _MemState()


def is_enabled():
    return _flag("FLAGS_monitor_memory")


# -- ledger ------------------------------------------------------------------

def _nbytes(arr):
    """Bytes of one array-like: ``nbytes`` when the object has it,
    else shape x dtype itemsize (ShapeDtypeStructs in AOT plans)."""
    n = getattr(arr, "nbytes", None)
    if n is not None:
        return int(n)
    shape = getattr(arr, "shape", None)
    dtype = getattr(arr, "dtype", None)
    if shape is None or dtype is None:
        return 0
    size = 1
    for d in shape:
        size *= int(d)
    return size * int(getattr(dtype, "itemsize", 1) or 1)


def _entry(ent):
    """Normalize one provider entry into {tag, bytes, shape, dtype}.
    Accepted forms: ``(tag, array_like)``, ``(tag, nbytes_int)``, or a
    ready-made dict."""
    if isinstance(ent, dict):
        return {"tag": str(ent.get("tag")),
                "bytes": int(ent.get("bytes", 0)),
                "shape": ent.get("shape"), "dtype": ent.get("dtype")}
    tag, obj = ent
    if isinstance(obj, (int, float)):
        return {"tag": str(tag), "bytes": int(obj), "shape": None,
                "dtype": None}
    shape = getattr(obj, "shape", None)
    return {"tag": str(tag), "bytes": _nbytes(obj),
            "shape": list(shape) if shape is not None else None,
            "dtype": str(getattr(obj, "dtype", None))}


def register_component(name, provider, job="default"):
    """Register (or replace) one ledger component. ``provider()``
    returns an iterable of entries (see ``_entry``) or a dict
    ``{"entries": [...], "detail": {...}}``. Re-registration replaces
    the provider — engines re-constructed in tests must not grow the
    ledger without bound (the serving-metrics pruning discipline)."""
    with _state.lock:
        _state.components[(str(job), str(name))] = provider
    return name


def unregister_component(name, job="default"):
    with _state.lock:
        _state.components.pop((str(job), str(name)), None)


def allocator_stats():
    """The reconciliation witness. Device ``memory_stats()`` where the
    backend reports one; on backends that don't (CPU) the summed
    ``jax.live_arrays()`` nbytes. Consults jax ONLY when the process
    already imported it (``sys.modules`` probe) — a bare worker
    scraping /debugz/memory must not drag an accelerator backend in.
    Never raises."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {"source": "unavailable", "live_bytes": None,
                "peak_bytes": None, "limit_bytes": None}
    try:
        stats = None
        if jax.process_count() == 1:
            # multi-process guard: the per-step device query races the
            # in-flight collective transport (parallel/engine.py note)
            stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_in_use" in stats:
            return {"source": "device_memory_stats",
                    "live_bytes": int(stats["bytes_in_use"]),
                    "peak_bytes": int(stats.get("peak_bytes_in_use", 0))
                    or None,
                    "limit_bytes": int(stats.get("bytes_limit", 0))
                    or None}
        live, n = 0, 0
        for a in jax.live_arrays():
            live += _nbytes(a)
            n += 1
        return {"source": "live_arrays", "live_bytes": int(live),
                "live_arrays": n, "peak_bytes": None,
                "limit_bytes": None}
    except Exception as e:
        _registry.warn_once(
            "memory.allocator_stats",
            "paddle_tpu.monitor.memory: allocator witness unavailable "
            "(ledger stays unreconciled): %r" % (e,))
        return {"source": "unavailable", "live_bytes": None,
                "peak_bytes": None, "limit_bytes": None}


def device_capacity_bytes(stats=None):
    """HBM capacity for the headroom math: ``PT_MEM_CAPACITY_BYTES``
    override first (tests, CPU smoke), then the allocator's own
    ``bytes_limit``; None when neither exists (headroom then absent,
    never fabricated)."""
    raw = os.environ.get("PT_MEM_CAPACITY_BYTES")
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    if stats is None:
        stats = allocator_stats()
    return stats.get("limit_bytes")


def note_transient_peak(job, nbytes, source="caller"):
    """Record the compiled-step transient peak for ``job`` — the
    donation-aware ``executable_analysis``/``graph_report()`` number
    (``compiled_peak``), which the headroom math adds to the static
    ledger."""
    with _state.lock:
        _state.transient[str(job)] = {"bytes": int(nbytes),
                                      "source": str(source)}


def transient_peak(job):
    """{bytes, source} of the best-known compiled transient peak for
    ``job``: an explicit ``note_transient_peak`` first, else the
    ``hbm_peak_bytes{job}`` gauge perf attribution publishes."""
    with _state.lock:
        rec = _state.transient.get(str(job))
    if rec is not None:
        return dict(rec)
    g = _registry.get_registry().get("hbm_peak_bytes")
    if g is not None:
        for key, v in g.collect():
            if key == (str(job),) and isinstance(v, (int, float)) \
                    and v > 0:
                return {"bytes": int(v), "source": "hbm_peak_bytes"}
    return None


def compiled_peak(compiled):
    """Donation-aware HBM peak of one compiled executable — THE shared
    peak number (monitor/perf.py ``executable_analysis``: the real
    buffer-assignment peak when jaxlib reports one, else args + temps
    + outputs net of donation aliasing, flagged as an estimate).
    tools/llama7b_plan.py and the graph_report() cost rows both
    consume this instead of hand-rolling the fallback. Returns
    ``(peak_bytes_or_None, is_estimate)``. ``memory_only`` skips the
    cost_analysis FLOPs walk the peak never needed."""
    a = _perf.executable_analysis(compiled, memory_only=True)
    return a.get("hbm_peak_bytes"), bool(a.get("hbm_peak_is_estimate"))


def sample():
    """Walk every registered provider, publish the ``mem_*`` gauges,
    reconcile against the allocator witness, and return the breakdown
    dict (the /debugz/memory core). Never raises: a provider dying
    marks ITS component and the rest of the ledger still reports."""
    with _state.lock:
        items = sorted(_state.components.items())
    components = {}
    job_totals = {}
    arrays = []
    for (job, name), provider in items:
        try:
            raw = provider() or ()
        except Exception as e:
            _registry.warn_once(
                "memory.provider.%s.%s" % (job, name),
                "paddle_tpu.monitor.memory: provider %s/%s raised "
                "(component reports error, ledger continues): %r"
                % (job, name, e))
            components.setdefault(job, {})[name] = {
                "bytes": 0, "entries": 0, "error": repr(e)}
            continue
        detail = None
        if isinstance(raw, dict):
            detail = raw.get("detail")
            raw = raw.get("entries") or ()
        ents = [_entry(e) for e in raw]
        total = sum(e["bytes"] for e in ents)
        comp = {"bytes": total, "entries": len(ents)}
        if detail:
            comp["detail"] = dict(detail)
        components.setdefault(job, {})[name] = comp
        job_totals[job] = job_totals.get(job, 0) + total
        for e in ents:
            arrays.append(dict(e, component=name, job=job))
        _MEM_DEV.labels(component=name, job=job).set(total)
    stats = allocator_stats()
    ledger_total = sum(job_totals.values())
    unattributed = None
    if stats["live_bytes"] is not None:
        _MEM_DEV.labels(component="allocator",
                        job="device").set(stats["live_bytes"])
        unattributed = stats["live_bytes"] - ledger_total
        _MEM_UNATTRIBUTED.labels(job="device").set(unattributed)
    cap = device_capacity_bytes(stats)
    jobs = {}
    for job, total in sorted(job_totals.items()):
        peak = transient_peak(job)
        row = {"ledger_bytes": total,
               "transient_peak_bytes": peak["bytes"] if peak else None,
               "transient_peak_source": peak["source"] if peak
               else None,
               "capacity_bytes": cap, "headroom_bytes": None}
        if cap is not None:
            # headroom subtracts the FULL static ledger (every job's
            # components share the one device), plus THIS job's
            # transient peak — two jobs on one chip must not each
            # claim the other's bytes as free
            row["headroom_bytes"] = int(
                cap - ledger_total - (peak["bytes"] if peak else 0))
            _MEM_HEADROOM.labels(job=job).set(row["headroom_bytes"])
        jobs[job] = row
    arrays.sort(key=lambda a: -a["bytes"])
    return {
        "components": components,
        "jobs": jobs,
        "top_arrays": arrays[:_TOP_K],
        "reconciliation": {
            "source": stats["source"],
            "live_bytes": stats["live_bytes"],
            "ledger_bytes": ledger_total,
            "unattributed_bytes": unattributed,
            "tolerance": RECONCILE_TOLERANCE,
        },
    }


# -- decision ring (OOM-postmortem context) ----------------------------------

def note_decision(job, kind, **info):
    """Record one scheduler decision (admit / preempt / shed) into the
    bounded ring the OOM postmortem embeds — "what was the engine
    doing to the pool right before it died". Monotonic stamp: the
    postmortem orders and ages these, never a wall clock."""
    rec = {"job": str(job), "kind": str(kind),
           "t_mono": time.monotonic()}
    rec.update(info)
    with _state.lock:
        _state.decisions.append(rec)
        if len(_state.decisions) > _DECISIONS_CAP:
            del _state.decisions[:len(_state.decisions)
                                 - _DECISIONS_CAP]


def recent_decisions(k=16):
    with _state.lock:
        return list(_state.decisions[-int(k):])


# -- OOM forensics -----------------------------------------------------------

def looks_like_oom(exc):
    """OOM classification: XLA RESOURCE_EXHAUSTED shapes, plus the
    deterministic ``mem.oom`` injection site (CPU-testable stand-in —
    a real 16 GB exhaustion cannot run in CI)."""
    try:
        from ..resilience.faultinject import InjectedFault

        if isinstance(exc, InjectedFault) and exc.site == "mem.oom":
            return True
    except Exception as e:
        _registry.warn_once(
            "memory.oom_classify",
            "paddle_tpu.monitor.memory: fault-inject import failed "
            "during OOM classification (marker match still runs): %r"
            % (e,))
    msg = "%s: %s" % (type(exc).__name__, exc)
    return any(m in msg for m in _OOM_MARKERS)


def _rank():
    try:
        from ..distributed import process_group as _pg

        pg = _pg.get_world_group()
        if pg is not None:
            return int(pg.rank)
    except Exception as e:
        _registry.warn_once(
            "memory.rank",
            "paddle_tpu.monitor.memory: world-group rank lookup "
            "failed (postmortem files as rank from env/0): %r" % (e,))
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    except ValueError:
        return 0


def write_postmortem(job, exc, context=None):
    """Emit ``oom_postmortem_rank{r}.json`` (PT_MONITOR_DUMP_DIR):
    ledger breakdown + top-K live arrays, caller context (KV occupancy
    etc.), the recent decision ring, and the last-K ``mem_*`` ring
    tails. NEVER raises and never recovers — the caller re-raises the
    original failure; this only makes sure the evidence outlives the
    process. Returns the written path or None."""
    try:
        rank = _rank()
        try:
            breakdown = sample()
        except Exception as e:   # the ledger itself must not mask the OOM
            breakdown = {"error": repr(e)}
        post = {
            "kind": "oom_postmortem",
            "version": 1,
            "job": str(job),
            "rank": rank,
            "pid": os.getpid(),
            "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime()),
            "unix_time": time.time(),
            "error": repr(exc),
            "error_type": type(exc).__name__,
            "injected": type(exc).__name__ == "InjectedFault",
            "ledger": breakdown,
            "context": dict(context) if context else {},
            "decisions": recent_decisions(),
            "mem_ring_tails": _timeseries.tail(prefixes=("mem_",),
                                               k=32),
        }
        d = os.environ.get("PT_MONITOR_DUMP_DIR") or "."
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "oom_postmortem_rank%d.json" % rank)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(post, f, indent=1, default=str)
            f.write("\n")
        os.replace(tmp, path)
    except Exception as e:
        _registry.warn_once(
            "memory.postmortem",
            "paddle_tpu.monitor.memory: OOM postmortem write failed "
            "(original failure re-raises regardless): %r" % (e,))
        return None
    _OOM_TOTAL.labels(job=str(job)).inc()
    with _state.lock:
        _state.postmortems.append({
            "path": path, "job": str(job), "rank": rank,
            "unix_time": post["unix_time"], "error": repr(exc)})
        if len(_state.postmortems) > _POSTMORTEMS_CAP:
            del _state.postmortems[:len(_state.postmortems)
                                   - _POSTMORTEMS_CAP]
    # ptslo (monitor/incidents.py): an OOM is a page-severity incident
    # with the postmortem as evidence. It never auto-resolves — the
    # process is about to re-raise; resolution is a human (or fleet
    # restart) decision. Lazy import, one flag branch while off.
    try:
        from . import incidents as _incidents

        _incidents.open(
            "oom/%s" % (job,), severity="page", kind="oom",
            source="memory", rank=rank,
            summary="OOM in job %s on rank %d: %s"
            % (job, rank, type(exc).__name__),
            evidence={"postmortem": path, "error": repr(exc)})
    except Exception as e:
        _registry.warn_once(
            "memory.incident_open",
            "paddle_tpu.monitor.memory: OOM incident open failed "
            "(postmortem was still written): %r" % (e,))
    return path


# -- leak sentinel -----------------------------------------------------------

class MemLeakSentinel(_perf.Sentinel):
    """Steady-state growth of live bytes: a full window of
    never-decreasing samples whose total growth clears
    ``min_growth_bytes`` (and spans ``min_window_s`` of MONOTONIC
    time) fires ``perf_anomalies_total{kind="mem_leak"}`` — which
    flips /healthz to degraded via the existing perf plumbing. Warmup
    is the base-class guarantee: a clean warmup can never fire. Any
    single decreasing sample (a release, a preemption reclaim) resets
    the verdict — sawtooth occupancy is load, monotone growth is a
    leak."""

    kind = "mem_leak"

    def __init__(self, series="mem_device_bytes", warmup=8, window=6,
                 min_growth_bytes=1 << 20, min_window_s=0.0):
        super().__init__(series, warmup=warmup)
        self.window = int(window)
        self.min_growth = int(min_growth_bytes)
        self.min_window_s = float(min_window_s)

    def check(self, st, value):
        win = st.get("win") or []
        if len(win) < self.window:
            return None
        vals = [v for _, v in win]
        if any(b < a for a, b in zip(vals, vals[1:])):
            return None
        if value < vals[-1]:
            return None
        growth = value - vals[0]
        if growth < self.min_growth:
            return None
        span = time.monotonic() - win[0][0]
        if span < self.min_window_s:
            return None
        st["leaking"] = True
        return {"growth_bytes": growth, "window": self.window,
                "window_s": span, "first_bytes": vals[0],
                "last_bytes": value}

    def update(self, st, value):
        win = st.setdefault("win", [])
        # a decreasing sample is the sawtooth reset that already clears
        # the verdict — while a leak episode is latched it is also the
        # recovery edge the incident table resolves on
        if st.get("leaking") and win and value < win[-1][1]:
            st["leaking"] = False
            st["recovered"] = True
        # window stamps are our OWN monotonic reads, not the ring's
        # wall ts — the span math must survive an NTP step mid-window
        win.append((time.monotonic(), value))
        if len(win) > self.window:
            del win[:len(win) - self.window]


def _ensure_leak_sentinel():
    """Install the leak sentinel once (enabling the ring + listener it
    reads, the ``perf.add_sentinel`` contract)."""
    with _state.lock:
        if _state.sentinel is not None:
            return _state.sentinel
        s = _state.sentinel = MemLeakSentinel()
    _perf.add_sentinel(s)
    return s


# -- construction-latch tracker (the engine-facing API) ----------------------

class MemTracker:
    """One engine's latched handle: decisions, transient peaks and
    postmortems route through it so the hot path never re-reads the
    flag (ptlint hot-path-latch discipline)."""

    __slots__ = ("job", "_context_fn")

    def __init__(self, job, context_fn=None):
        self.job = job
        self._context_fn = context_fn

    def note_decision(self, kind, **info):
        note_decision(self.job, kind, **info)

    def note_transient_peak(self, nbytes, source="engine"):
        note_transient_peak(self.job, nbytes, source)

    def write_postmortem(self, exc):
        ctx = None
        if self._context_fn is not None:
            try:
                ctx = self._context_fn()
            except Exception as e:
                ctx = {"context_error": repr(e)}
        return write_postmortem(self.job, exc, context=ctx)


def tracker(job, components, context_fn=None):
    """THE construction-latch entry point: when ``FLAGS_monitor_memory``
    is on, register ``components`` ({name: provider}) under ``job``,
    arm the leak sentinel, and return a ``MemTracker``; when off,
    return None — one flag read at construction, and the hot path only
    ever checks the handle."""
    if not is_enabled():
        return None
    for name, provider in components.items():
        register_component(name, provider, job=job)
    _ensure_leak_sentinel()
    return MemTracker(job, context_fn)


# -- payload / reset ---------------------------------------------------------

def memory_payload():
    """The /debugz/memory JSON body. Off = pinned
    ``{"enabled": false}`` shape with empty collections (route answers
    200 either way — "off" is a payload, not an error)."""
    enabled = is_enabled()
    out = {"enabled": enabled, "time": time.time(),
           "components": {}, "jobs": {}, "decisions": [],
           "postmortems": []}
    if not enabled:
        return out
    out.update(sample())
    out["decisions"] = recent_decisions()
    with _state.lock:
        out["postmortems"] = list(_state.postmortems)
        s = _state.sentinel
    out["leak_sentinel"] = None if s is None else {
        "series": s.series, "warmup": s.warmup, "window": s.window,
        "min_growth_bytes": s.min_growth,
        "min_window_s": s.min_window_s}
    return out


def reset():
    """Test hook: forget components/decisions/postmortems/peaks, drop
    the published ``mem_*`` series (flags-off after reset is pinned
    series-free), and detach the leak sentinel."""
    with _state.lock:
        _state.components = {}
        _state.decisions = []
        _state.postmortems = []
        _state.transient = {}
        s, _state.sentinel = _state.sentinel, None
    if s is not None:
        try:
            _perf._state.sentinels.remove(s)
        except ValueError:
            pass
    for g in (_MEM_DEV, _MEM_HEADROOM, _MEM_UNATTRIBUTED, _OOM_TOTAL):
        for key in list(g._children):
            g.remove(*key)


# env/FLAGS bootstrap (the timeseries/perf discipline): a process
# started with FLAGS_monitor_memory=1 has the leak sentinel armed from
# its first sample without any code change.
if _flag("FLAGS_monitor_memory"):
    _ensure_leak_sentinel()
