"""Metric registry: Counter/Gauge/Histogram primitives with labels.

The framework-wide aggregation point (reference platform/monitor.cc
StatRegistry generalized to labeled series): every subsystem —
serving/metrics.py, the compiled train step (parallel/engine.py),
fleet/metrics.py reductions — registers its samples here, and the one
registry exports them as a JSON snapshot or Prometheus exposition text
(monitor/exporter.py serves both over HTTP).

Design constraints:

- **Near-zero overhead when disabled.** Every mutator
  (``inc``/``set``/``observe``) checks a module-level enabled flag
  before touching locks, dicts, or the native lib — the disabled fast
  path is one attribute load + branch, asserted native-call-free by
  tests/test_monitor.py.
- **No hard native dependency.** The optional chrome-trace bridge
  mirrors Counter/Gauge samples onto the native counter timeline
  (csrc/trace.cc ``pt_trace_counter``) so registry series line up with
  RecordEvent spans in merged traces; a build without the lib degrades
  to pure-python silently.
- **Idempotent construction.** ``counter()/gauge()/histogram()``
  return the already-registered metric when called twice with the same
  name (engines and train steps are constructed repeatedly in tests) —
  mismatched kind or labelnames is a real error.
"""
from __future__ import annotations

import os
import threading
import time


class _State:
    __slots__ = ("enabled", "trace_bridge", "_trace_fn", "ts_hook",
                 "ex_hook")

    def __init__(self):
        self.enabled = os.environ.get("PT_MONITOR", "1").lower() \
            not in ("0", "false", "off")
        self.trace_bridge = os.environ.get(
            "PT_MONITOR_TRACE", "0").lower() in ("1", "true", "on")
        self._trace_fn = None
        # time-series ring hook (monitor/timeseries.py installs it):
        # None = the ring is off and mutators pay exactly one extra
        # attribute-load + branch — the same disabled-path discipline
        # as trace_bridge, pinned by tests/test_perf.py
        self.ts_hook = None
        # histogram exemplar hook (monitor/trace.py installs it):
        # None = the span journal is off and observes pay one extra
        # attribute-load + branch, pinned by tests/test_trace.py
        self.ex_hook = None


_state = _State()

# -- warn-once (the silent-except replacement) -------------------------------
# Diagnostic threads must not eat their own failures invisibly (ptlint
# silent-except discipline), but a collector hitting the same transient
# error every 2s scrape must not flood stderr either: one line per key.
_warned = set()
_warned_lock = threading.Lock()


def warn_once(key, msg):
    """Write ``msg`` to stderr the FIRST time ``key`` is seen."""
    with _warned_lock:
        if key in _warned:
            return False
        _warned.add(key)
    import sys

    try:
        sys.stderr.write(msg.rstrip("\n") + "\n")
    # ptlint: silent-except-ok — warn_once is the sink every never-raise
    # diagnostic path drains into; a closed/replaced/None stderr (pytest
    # capsys teardown, interpreter shutdown) must not re-raise there
    except Exception:
        pass
    return True


def enable(trace_bridge=None):
    """Turn metric collection on (process-wide). ``trace_bridge=True``
    additionally mirrors Counter/Gauge samples onto the native
    chrome-trace counter timeline."""
    _state.enabled = True
    if trace_bridge is not None:
        _state.trace_bridge = bool(trace_bridge)
        if not trace_bridge:
            _state._trace_fn = None


def disable():
    """Turn collection off: every mutator becomes an early return."""
    _state.enabled = False


def is_enabled():
    return _state.enabled


def _trace_counter(name, value):
    """Best-effort mirror onto the native trace counter timeline. The
    native API is int64 (csrc/trace.cc pt_trace_counter): FLOAT samples
    are scaled x1000 under a ``_milli`` suffix so sub-1.0 gauges (AUC,
    occupancy, sub-second rates) don't flatline at 0. The decision is
    by sample TYPE, not value — a metric that always reports floats
    stays on one consistently-scaled series even when a sample lands on
    a whole number (0.8 -> 800, 2.0 -> 2000, never a bare 2)."""
    fn = _state._trace_fn
    if fn is None:
        try:
            from ..core import native

            lib = native.get_lib()
            fn = lib.pt_trace_counter
        except Exception:
            # no native lib in this build: degrade to pure python and
            # stop probing (flip the bridge off so the fast path stays
            # fast)
            _state.trace_bridge = False
            return
        _state._trace_fn = fn
    if isinstance(value, float):
        name += "_milli"
        value = round(value * 1000)
    try:
        fn(name.encode(), int(value))
    except Exception:
        _state.trace_bridge = False


# -- metric primitives -------------------------------------------------------

class _Child:
    """One labeled series of a metric."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric, key):
        self._metric = metric
        self._key = key


class _CounterChild(_Child):
    def inc(self, amount=1):
        if not _state.enabled:
            return
        self._metric._add(self._key, amount)

    @property
    def value(self):
        return self._metric._values.get(self._key, 0)


class _GaugeChild(_Child):
    def set(self, value):
        if not _state.enabled:
            return
        self._metric._set(self._key, value)

    def inc(self, amount=1):
        if not _state.enabled:
            return
        self._metric._add(self._key, amount)

    def dec(self, amount=1):
        self.inc(-amount)

    @property
    def value(self):
        return self._metric._values.get(self._key, 0)


class _HistogramChild(_Child):
    def observe(self, value):
        if not _state.enabled:
            return
        self._metric._observe(self._key, value)

    def time(self):
        """Context manager observing the elapsed seconds of the block."""
        return _Timer(self)


class _Timer:
    __slots__ = ("_child", "_t0")

    def __init__(self, child):
        self._child = child

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._child.observe(time.perf_counter() - self._t0)


class _DetachedSink:
    """Write target for children whose series was remove()d: absorbs
    samples without re-creating registry state."""

    _values = {}

    def _add(self, key, amount):
        pass

    def _set(self, key, value):
        pass

    def _observe(self, key, value):
        pass


_DETACHED = _DetachedSink()


class Metric:
    kind = "untyped"

    def __init__(self, name, help="", labelnames=(), registry=None):
        if not name or not name.replace("_", "a").replace(":", "a").isalnum():
            raise ValueError("invalid metric name %r" % name)
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children = {}
        reg = registry if registry is not None else get_registry()
        if reg.register(self) is not self:
            # a matched duplicate would silently orphan this instance
            # (its samples never reach the exporters) — force sharing
            # through the idempotent constructors instead
            raise ValueError(
                "metric %r is already registered; use "
                "monitor.counter/gauge/histogram() to share it"
                % name)

    def labels(self, *values, **kw):
        """Bind label values; returns the per-series child."""
        if kw:
            if values:
                raise ValueError("pass label values positionally OR by name")
            unknown = set(kw) - set(self.labelnames)
            missing = set(self.labelnames) - set(kw)
            if unknown or missing:
                raise ValueError(
                    "%s expects labels %s; unknown %s, missing %s"
                    % (self.name, self.labelnames,
                       sorted(unknown), sorted(missing)))
            values = tuple(kw[n] for n in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                "%s expects labels %s, got %r"
                % (self.name, self.labelnames, values))
        values = tuple(str(v) for v in values)
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(
                    values, self._child_cls(self, values))
        return child

    def remove(self, *values, **kw):
        """Drop one labeled series (child binding and recorded data) —
        the hook that keeps per-instance label dimensions (e.g.
        ``engine=<id>``) from growing without bound. A still-live child
        bound to the removed series is DETACHED: its writes become
        no-ops rather than silently resurrecting the series outside the
        registry's pruning view."""
        if kw:
            values = tuple(kw[n] for n in self.labelnames)
        values = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.pop(values, None)
            if child is not None:
                child._metric = _DETACHED
            for attr in ("_values", "_series"):
                store = getattr(self, attr, None)
                if store is not None:
                    store.pop(values, None)

    def _default_child(self):
        return self.labels(*(() if not self.labelnames else
                             ("",) * len(self.labelnames)))

    def _series_name(self, key):
        return _series(self.name, self.labelnames, key)


class Counter(Metric):
    """Monotone counter. ``inc`` on the metric itself operates on the
    unlabeled series (only valid without labelnames)."""

    kind = "counter"
    _child_cls = _CounterChild

    def __init__(self, name, help="", labelnames=(), registry=None):
        super().__init__(name, help, labelnames, registry)
        self._values = {}

    def _add(self, key, amount):
        if amount < 0:
            raise ValueError("counters only go up (inc(%r))" % (amount,))
        with self._lock:
            v = self._values.get(key, 0) + amount
            self._values[key] = v
        if _state.trace_bridge:
            _trace_counter(self._series_name(key), v)
        if _state.ts_hook is not None:
            _state.ts_hook(self, key, v)

    def inc(self, amount=1):
        if not _state.enabled:
            return
        if self.labelnames:
            raise ValueError("%s has labels; use .labels(...)" % self.name)
        self._add((), amount)

    @property
    def value(self):
        return self._values.get((), 0)

    def collect(self):
        with self._lock:
            return [(key, v) for key, v in sorted(self._values.items())]


class Gauge(Counter):
    """Last-write-wins instantaneous value (can go down)."""

    kind = "gauge"
    _child_cls = _GaugeChild

    def _add(self, key, amount):
        with self._lock:
            v = self._values.get(key, 0) + amount
            self._values[key] = v
        if _state.trace_bridge:
            _trace_counter(self._series_name(key), v)
        if _state.ts_hook is not None:
            _state.ts_hook(self, key, v)

    def _set(self, key, value):
        with self._lock:
            self._values[key] = value
        if _state.trace_bridge:
            _trace_counter(self._series_name(key), value)
        if _state.ts_hook is not None:
            _state.ts_hook(self, key, value)

    def set(self, value):
        if not _state.enabled:
            return
        if self.labelnames:
            raise ValueError("%s has labels; use .labels(...)" % self.name)
        self._set((), value)

    def dec(self, amount=1):
        self.inc(-amount)


# default buckets: request-latency shaped (prometheus client defaults)
DEFAULT_BUCKETS = (.001, .0025, .005, .01, .025, .05, .1, .25, .5,
                   1.0, 2.5, 5.0, 10.0)


class Histogram(Metric):
    kind = "histogram"
    _child_cls = _HistogramChild

    def __init__(self, name, help="", labelnames=(), buckets=None,
                 registry=None):
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        super().__init__(name, help, labelnames, registry)
        self._series = {}  # key -> [bucket_counts..., sum, count]

    def _observe(self, key, value):
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = \
                    [0] * len(self.buckets) + [0.0, 0]
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s[i] += 1
            s[-2] += value
            s[-1] += 1
        if _state.ts_hook is not None:
            # histograms ring the RAW observation (not the cumulative
            # sum): train_step_seconds' ring is the per-step trace a
            # hang postmortem wants
            _state.ts_hook(self, key, value)
        if _state.ex_hook is not None:
            _state.ex_hook(self, key, value)

    def observe(self, value):
        if not _state.enabled:
            return
        if self.labelnames:
            raise ValueError("%s has labels; use .labels(...)" % self.name)
        self._observe((), value)

    def time(self):
        return _Timer(self._default_child() if self.labelnames
                      else _HistogramChild(self, ()))

    def collect(self):
        with self._lock:
            out = []
            for key, s in sorted(self._series.items()):
                out.append((key, {
                    "buckets": dict(zip(self.buckets, s[:-2])),
                    "sum": s[-2], "count": s[-1],
                }))
            return out


# -- registry ----------------------------------------------------------------

class Registry:
    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def register(self, metric):
        with self._lock:
            have = self._metrics.get(metric.name)
            if have is not None and have is not metric:
                if (have.kind, have.labelnames) != (metric.kind,
                                                    metric.labelnames):
                    raise ValueError(
                        "metric %r already registered as %s%s"
                        % (metric.name, have.kind, have.labelnames))
                return have
            self._metrics[metric.name] = metric
            return metric

    def get(self, name):
        return self._metrics.get(name)

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self):
        with self._lock:
            self._metrics.clear()

    def metrics(self):
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    # -- exporters ---------------------------------------------------------

    def snapshot(self):
        """JSON-ready dict: {name: {kind, help, series: [...]}}."""
        out = {}
        for m in self.metrics():
            series = []
            if m.kind in ("counter", "gauge"):
                for key, v in m.collect():
                    series.append({
                        "labels": dict(zip(m.labelnames, key)),
                        "value": v,
                    })
            else:
                for key, h in m.collect():
                    series.append({
                        "labels": dict(zip(m.labelnames, key)),
                        "sum": h["sum"], "count": h["count"],
                        "buckets": {str(b): c
                                    for b, c in h["buckets"].items()},
                    })
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "series": series}
        return out

    def prometheus_text(self):
        """Prometheus text exposition format (version 0.0.4)."""
        lines = []
        for m in self.metrics():
            if m.help:
                lines.append("# HELP %s %s"
                             % (m.name, m.help.replace("\n", " ")))
            lines.append("# TYPE %s %s" % (m.name, m.kind))
            if m.kind in ("counter", "gauge"):
                for key, v in m.collect():
                    lines.append("%s %s"
                                 % (_series(m.name, m.labelnames, key),
                                    _fmt(v)))
            else:
                for key, h in m.collect():
                    bnames = list(m.labelnames) + ["le"]
                    for b, c in h["buckets"].items():
                        lines.append("%s %d" % (_series(
                            m.name + "_bucket", bnames,
                            list(key) + [_fmt(b)]), c))
                    lines.append("%s %d" % (_series(
                        m.name + "_bucket", bnames,
                        list(key) + ["+Inf"]), h["count"]))
                    lines.append("%s %s"
                                 % (_series(m.name + "_sum", m.labelnames,
                                            key), _fmt(h["sum"])))
                    lines.append("%s %d"
                                 % (_series(m.name + "_count",
                                            m.labelnames, key),
                                    h["count"]))
        return "\n".join(lines) + "\n"


def _fmt(v):
    if isinstance(v, float):
        # non-finite samples are legal (a NaN loss gauge IS the perf
        # sentinel's input) — exposition-format spellings, never a
        # crashed /metrics scrape mid-incident
        if v != v:
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        if v == float("-inf"):
            return "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return "%g" % v
        return repr(v)
    return str(v)


def _series(name, labelnames, key):
    if not labelnames:
        return name
    lbl = ",".join('%s="%s"' % (n, str(v).replace('"', '\\"'))
                   for n, v in zip(labelnames, key))
    return "%s{%s}" % (name, lbl)


_default_registry = Registry()


def get_registry():
    return _default_registry


# -- idempotent constructors (the module-level metric idiom) -----------------

def _check_match(have, cls, name, labelnames):
    if (have.kind, have.labelnames) != (cls.kind, tuple(labelnames)):
        raise ValueError(
            "metric %r already registered as %s%s"
            % (name, have.kind, have.labelnames))
    return have


def _get_or_create(cls, name, help, labelnames, **kw):
    have = _default_registry.get(name)
    if have is not None:
        return _check_match(have, cls, name, labelnames)
    try:
        return cls(name, help=help, labelnames=labelnames, **kw)
    except ValueError:
        # lost a registration race: fall back to the winner if it
        # matches, else surface the mismatch
        have = _default_registry.get(name)
        if have is None:
            raise
        return _check_match(have, cls, name, labelnames)


def counter(name, help="", labelnames=()):
    return _get_or_create(Counter, name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return _get_or_create(Gauge, name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    h = _get_or_create(Histogram, name, help, labelnames,
                       buckets=buckets)
    want = tuple(sorted(buckets or DEFAULT_BUCKETS))
    if h.buckets != want:
        # observations would silently land in the wrong boundaries —
        # bucket disagreement is as real a conflict as a kind mismatch
        raise ValueError(
            "histogram %r already registered with buckets %s (asked "
            "for %s)" % (name, h.buckets, want))
    return h
