"""Reference-format inference-model importer.

Reads a model saved by the reference framework's
`paddle.static.save_inference_model` — a ProgramDesc protobuf
(`.pdmodel` / `__model__`, reference
paddle/fluid/framework/framework.proto:242) plus parameters in the
combined stream format (`.pdiparams`, written by the save_combine op in
sorted-variable-name order, reference python/paddle/static/io.py:399 and
paddle/fluid/framework/tensor_util.cc:660 TensorToStream) — and lowers
it onto this framework: parameters become jnp arrays, the op list
executes through per-op adapters onto the same jnp/lax bodies the
native dispatch uses.

No reference code is used: the protobuf wire format is decoded by a
~100-line generic reader driven by the message field numbers (public
interface facts from framework.proto), and each op adapter is an
original jnp implementation.

Scope: the inference op subset covering LeNet / ResNet-class vision
models, feed-forward nets, and transformer encoders (ERNIE/BERT-class:
lookup_table embeddings, layer_norm, matmul_v2 with transposes, the
reshape/transpose/stack/slice/concat/split manipulation family, and the
scale+softmax attention composition). Unknown ops raise a typed
UnimplementedError naming the op so coverage gaps are loud, not silent.
"""
from __future__ import annotations

import struct

import numpy as np

from ..core.enforce import UnimplementedError

# -- protobuf wire-format reader (generic, schema-driven) -------------------


def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed(v):
    """Interpret an unsigned varint as two's-complement int64."""
    v &= (1 << 64) - 1
    return v - (1 << 64) if v >= (1 << 63) else v


def parse_fields(buf):
    """bytes -> {field_number: [(wire_type, raw_value), ...]}"""
    fields = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        fnum, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = _read_varint(buf, pos)
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError("unsupported wire type %d" % wt)
        fields.setdefault(fnum, []).append((wt, v))
    return fields


def _scalar(fields, num, default=None):
    vals = fields.get(num)
    if not vals:
        return default
    wt, v = vals[-1]
    if wt == 0:
        return v
    if wt == 2:
        return v
    if wt == 5:
        return struct.unpack("<f", v)[0]
    if wt == 1:
        return struct.unpack("<d", v)[0]
    return v


def _string(fields, num, default=None):
    v = _scalar(fields, num, None)
    return v.decode("utf-8") if isinstance(v, bytes) else default


def _repeated_varint(fields, num, signed=False):
    out = []
    for wt, v in fields.get(num, []):
        if wt == 0:
            out.append(_signed(v) if signed else v)
        elif wt == 2:  # packed
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(_signed(x) if signed else x)
    return out


def _repeated_f32(fields, num):
    out = []
    for wt, v in fields.get(num, []):
        if wt == 5:
            out.append(struct.unpack("<f", v)[0])
        elif wt == 2:  # packed
            out.extend(struct.unpack("<%df" % (len(v) // 4), v))
    return out


def _messages(fields, num):
    return [parse_fields(v) for wt, v in fields.get(num, []) if wt == 2]


# -- schema extraction (framework.proto field numbers) ----------------------

_DTYPES = {0: np.bool_, 1: np.int16, 2: np.int32, 3: np.int64,
           4: np.float16, 5: np.float32, 6: np.float64,
           20: np.uint8, 21: np.int8}


def _dtype_of(code):
    if code == 22:  # BF16 has no numpy dtype; ml_dtypes provides one
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return np.dtype(_DTYPES[code])
    except KeyError:
        raise UnimplementedError(
            "reference model uses unsupported tensor dtype code %d"
            % code)


class OpDesc:
    def __init__(self, fields):
        self.type = _string(fields, 3)
        self.inputs = {}
        for var in _messages(fields, 1):
            slot = _string(var, 1)
            self.inputs[slot] = [v.decode("utf-8")
                                 for wt, v in var.get(2, []) if wt == 2]
        self.outputs = {}
        for var in _messages(fields, 2):
            slot = _string(var, 1)
            self.outputs[slot] = [v.decode("utf-8")
                                  for wt, v in var.get(2, []) if wt == 2]
        self.attrs = {}
        for attr in _messages(fields, 4):
            name = _string(attr, 1)
            atype = _scalar(attr, 2, 0)
            if atype == 0:
                val = _signed(_scalar(attr, 3, 0))
            elif atype == 1:
                val = _scalar(attr, 4, 0.0)
            elif atype == 2:
                val = _string(attr, 5, "")
            elif atype == 3:
                val = [_signed(x) for x in _repeated_varint(attr, 6)]
            elif atype == 4:
                val = _repeated_f32(attr, 7)
            elif atype == 5:
                val = [v.decode("utf-8")
                       for wt, v in attr.get(8, []) if wt == 2]
            elif atype == 6:
                val = bool(_scalar(attr, 10, 0))
            elif atype == 7:
                val = [bool(x) for x in _repeated_varint(attr, 11)]
            elif atype == 9:
                val = _signed(_scalar(attr, 13, 0))
            elif atype == 11:
                val = [_signed(x)
                       for x in _repeated_varint(attr, 15, signed=True)]
            else:
                val = None  # blocks/vars attrs not needed for inference
            self.attrs[name] = val


class VarDesc:
    def __init__(self, fields):
        self.name = _string(fields, 1)
        self.persistable = bool(_scalar(fields, 3, 0))
        self.shape = None
        self.dtype = None
        vt = _messages(fields, 2)
        if vt:
            lod = _messages(vt[0], 3)
            if lod:
                td = _messages(lod[0], 1)
                if td:
                    self.dtype = _scalar(td[0], 1, 5)
                    self.shape = _repeated_varint(td[0], 2, signed=True)


class ProgramDesc:
    def __init__(self, data):
        fields = parse_fields(data)
        self.blocks = []
        for bf in _messages(fields, 1):
            block = {
                "vars": [VarDesc(v) for v in _messages(bf, 3)],
                "ops": [OpDesc(o) for o in _messages(bf, 4)],
            }
            self.blocks.append(block)
        if not self.blocks:
            raise ValueError("not a ProgramDesc: no blocks")


# -- parameter stream reader (tensor_util.cc TensorToStream layout) ---------


def read_tensor_stream(f):
    """One LoDTensor: u32 version, u64 lod_level (+levels), u32 version,
    i32 desc_size, TensorDesc proto, raw data."""
    head = f.read(4)
    if len(head) < 4:
        return None
    struct.unpack("<I", head)[0]  # LoDTensor version
    (lod_level,) = struct.unpack("<Q", f.read(8))
    for _ in range(lod_level):
        (sz,) = struct.unpack("<Q", f.read(8))
        f.read(sz)
    struct.unpack("<I", f.read(4))[0]  # tensor version
    (desc_size,) = struct.unpack("<i", f.read(4))
    desc = parse_fields(f.read(desc_size))
    dtype = _dtype_of(_scalar(desc, 1, 5))
    dims = _repeated_varint(desc, 2, signed=True)
    n = 1
    for d in dims:
        n *= d
    data = np.frombuffer(f.read(n * dtype.itemsize), dtype=dtype)
    return data.reshape(dims)


def read_combined_params(path, names_sorted):
    """save_combine writes tensors back-to-back in sorted-name order
    (reference static/io.py:399)."""
    out = {}
    with open(path, "rb") as f:
        for name in names_sorted:
            t = read_tensor_stream(f)
            if t is None:
                raise ValueError(
                    "param file ended early at %r (have %d/%d)"
                    % (name, len(out), len(names_sorted)))
            out[name] = t
    return out


# -- op adapters ------------------------------------------------------------


def _pool2d(x, a):
    import jax.numpy as jnp
    from jax import lax

    ksize = a.get("ksize", [1, 1])
    strides = a.get("strides", ksize)
    pads = a.get("paddings", [0, 0])
    ptype = a.get("pooling_type", "max")
    if a.get("global_pooling") or (a.get("adaptive")
                                   and list(ksize) == [1, 1]):
        red = jnp.max if ptype == "max" else jnp.mean
        return red(x, axis=(2, 3), keepdims=True)
    if a.get("adaptive"):
        # adaptive pool: ksize IS the output size; divisible inputs map
        # to an even window, anything else has no fixed-window
        # equivalent — fail loudly per the module contract
        oh, ow = int(ksize[0]), int(ksize[1])
        ih, iw = x.shape[2], x.shape[3]
        if ih % oh or iw % ow:
            raise UnimplementedError(
                "adaptive pool2d with non-divisible output size "
                "(%d,%d) for input (%d,%d)" % (oh, ow, ih, iw))
        ksize = [ih // oh, iw // ow]
        strides = list(ksize)
        pads = [0, 0]
    if len(pads) == 2:
        pads = [pads[0], pads[0], pads[1], pads[1]]
    pad_cfg = [(0, 0), (0, 0), (pads[0], pads[1]), (pads[2], pads[3])]
    window = (1, 1, ksize[0], ksize[1])
    stride = (1, 1, strides[0], strides[1])
    if ptype == "max":
        init = -jnp.inf
        y = lax.reduce_window(x, init, lax.max, window, stride, pad_cfg)
        return y
    y = lax.reduce_window(x, 0.0, lax.add, window, stride, pad_cfg)
    if a.get("exclusive", True):
        ones = jnp.ones_like(x)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, stride,
                                pad_cfg)
        return y / cnt
    return y / (ksize[0] * ksize[1])


def _conv2d(x, w, a):
    from jax import lax

    strides = a.get("strides", [1, 1])
    pads = a.get("paddings", [0, 0])
    dil = a.get("dilations", [1, 1])
    groups = a.get("groups", 1) or 1
    algo = a.get("padding_algorithm", "EXPLICIT")
    if algo == "SAME":
        padding = "SAME"
    elif algo == "VALID":
        padding = "VALID"
    else:
        if len(pads) == 2:
            padding = [(pads[0], pads[0]), (pads[1], pads[1])]
        else:
            padding = [(pads[0], pads[1]), (pads[2], pads[3])]
    return lax.conv_general_dilated(
        x, w, tuple(strides), padding, rhs_dilation=tuple(dil),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups)


def _matmul_like(x, y, trans_x=False, trans_y=False):
    import jax.numpy as jnp

    if trans_x:
        x = jnp.swapaxes(x, -1, -2)
    if trans_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def _mul(x, y, a):
    xd = a.get("x_num_col_dims", 1) or 1
    yd = a.get("y_num_col_dims", 1) or 1
    xs, ys = x.shape, y.shape
    xf = x.reshape(int(np.prod(xs[:xd])), -1)
    yf = y.reshape(int(np.prod(ys[:yd])), -1)
    out = xf @ yf
    return out.reshape(tuple(xs[:xd]) + tuple(ys[yd:]))


def _batch_norm_infer(x, scale, bias, mean, var, a):
    import jax.numpy as jnp

    eps = a.get("epsilon", 1e-5)
    sh = (1, -1) + (1,) * (x.ndim - 2)
    return ((x - mean.reshape(sh)) /
            jnp.sqrt(var.reshape(sh) + eps) * scale.reshape(sh)
            + bias.reshape(sh))


def _elementwise(op_name, x, y, a):
    import jax.numpy as jnp

    axis = a.get("axis", -1)
    if axis not in (-1, None) and y.ndim < x.ndim:
        sh = [1] * x.ndim
        for i, d in enumerate(y.shape):
            sh[axis + i] = d
        y = y.reshape(sh)
    fns = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide, "max": jnp.maximum, "min": jnp.minimum,
           "pow": jnp.power}
    return fns[op_name](x, y)


def _run_op(op, env):
    """Execute one OpDesc against the value environment."""
    import jax
    import jax.numpy as jnp

    t = op.type
    a = op.attrs

    def inp(slot, idx=0):
        names = op.inputs.get(slot) or []
        return env[names[idx]] if len(names) > idx else None

    def inps(slot):
        return [env[n] for n in op.inputs.get(slot) or []]

    def set_out(slot, val, idx=0):
        names = op.outputs.get(slot) or []
        if len(names) > idx:
            env[names[idx]] = val

    def no_tensor_operands(*slots):
        """Loud-not-silent contract: shape/index operands supplied as
        TENSOR inputs (StartsTensorList etc.) mean the attr values are
        placeholders — using them would be silently wrong."""
        for slot in slots:
            if op.inputs.get(slot):
                raise UnimplementedError(
                    "reference-model importer: op %r supplies %r as a "
                    "tensor input; only attribute-form shapes/indices "
                    "are supported" % (t, slot),
                    hint="re-export the model with static shapes")

    if t in ("feed", "fetch"):
        return
    if t in ("conv2d", "depthwise_conv2d"):
        set_out("Output", _conv2d(inp("Input"), inp("Filter"), a))
    elif t == "pool2d":
        set_out("Out", _pool2d(inp("X"), a))
    elif t == "batch_norm":
        set_out("Y", _batch_norm_infer(inp("X"), inp("Scale"),
                                       inp("Bias"), inp("Mean"),
                                       inp("Variance"), a))
    elif t in ("matmul_v2", "matmul"):
        out = _matmul_like(
            inp("X"), inp("Y"),
            a.get("trans_x", a.get("transpose_X", False)),
            a.get("trans_y", a.get("transpose_Y", False)))
        alpha = a.get("alpha", 1.0)
        if t == "matmul" and alpha not in (None, 1.0):
            out = out * alpha
        set_out("Out", out)
    elif t == "mul":
        set_out("Out", _mul(inp("X"), inp("Y"), a))
    elif t.startswith("elementwise_"):
        set_out("Out", _elementwise(t.split("_", 1)[1], inp("X"),
                                    inp("Y"), a))
    elif t == "relu":
        set_out("Out", jnp.maximum(inp("X"), 0))
    elif t == "sigmoid":
        set_out("Out", jax.nn.sigmoid(inp("X")))
    elif t == "tanh":
        set_out("Out", jnp.tanh(inp("X")))
    elif t in ("gelu",):
        set_out("Out", jax.nn.gelu(inp("X"),
                                   approximate=a.get("approximate",
                                                     False)))
    elif t == "softmax":
        set_out("Out", jax.nn.softmax(inp("X"), axis=a.get("axis", -1)))
    elif t in ("reshape2", "reshape"):
        no_tensor_operands("Shape", "ShapeTensor")
        x = inp("X")
        # reference reshape semantics: 0 copies the input dim at that
        # index, -1 is inferred (framework reshape_op contract)
        shape = [int(s) for s in (a.get("shape") or [])]
        shape = [x.shape[i] if s == 0 else s
                 for i, s in enumerate(shape)]
        set_out("Out", x.reshape(shape))
    elif t in ("flatten_contiguous_range", "flatten2", "flatten"):
        x = inp("X")
        start = a.get("start_axis", a.get("axis", 1)) or 0
        stop = a.get("stop_axis", x.ndim - 1)
        if t != "flatten_contiguous_range":
            stop = x.ndim - 1
        sh = (x.shape[:start]
              + (int(np.prod(x.shape[start:stop + 1])),)
              + x.shape[stop + 1:])
        set_out("Out", x.reshape(sh))
    elif t == "scale":
        x = inp("X")
        s, b = a.get("scale", 1.0), a.get("bias", 0.0)
        if a.get("bias_after_scale", True):
            set_out("Out", x * s + b)
        else:
            set_out("Out", (x + b) * s)
    elif t == "dropout":
        x = inp("X")
        if a.get("dropout_implementation",
                 "downgrade_in_infer") == "upscale_in_train":
            set_out("Out", x)
        else:
            set_out("Out", x * (1.0 - a.get("dropout_prob", 0.5)))
    elif t == "fill_constant":
        shape = a.get("shape") or []
        set_out("Out", jnp.full([int(s) for s in shape],
                                a.get("value", 0.0),
                                _dtype_of(a.get("dtype", 5))))
    elif t == "transpose2" or t == "transpose":
        set_out("Out", jnp.transpose(inp("X"), a.get("axis")))
    elif t == "arg_max":
        set_out("Out", jnp.argmax(inp("X"), axis=a.get("axis", -1)))
    elif t == "mean":
        set_out("Out", jnp.mean(inp("X")))
    elif t == "layer_norm":
        x = inp("X")
        eps = a.get("epsilon", 1e-5)
        bna = a.get("begin_norm_axis", 1) or 1
        red = tuple(range(bna, x.ndim))
        m = jnp.mean(x, axis=red, keepdims=True)
        v = jnp.mean(jnp.square(x - m), axis=red, keepdims=True)
        y = (x - m) / jnp.sqrt(v + eps)
        norm_shape = x.shape[bna:]
        scale, bias = inp("Scale"), inp("Bias")
        if scale is not None:
            y = y * scale.reshape(norm_shape)
        if bias is not None:
            y = y + bias.reshape(norm_shape)
        set_out("Y", y)
    elif t in ("lookup_table_v2", "lookup_table"):
        w, ids = inp("W"), inp("Ids")
        if t == "lookup_table" and ids.ndim >= 2 and ids.shape[-1] == 1:
            ids = ids[..., 0]  # v1 carries a trailing [.., 1] dim
        ids = ids.astype(jnp.int32)
        out = jnp.take(w, ids, axis=0)
        pad = a.get("padding_idx", -1)
        if pad is not None and pad != -1:
            if pad < 0:
                pad += w.shape[0]
            out = jnp.where((ids == pad)[..., None],
                            jnp.zeros((), out.dtype), out)
        set_out("Out", out)
    elif t == "stack":
        set_out("Y", jnp.stack(inps("X"), axis=a.get("axis", 0) or 0))
    elif t == "concat":
        no_tensor_operands("AxisTensor")
        set_out("Out", jnp.concatenate(inps("X"),
                                       axis=a.get("axis", 0) or 0))
    elif t == "split":
        no_tensor_operands("AxisTensor", "SectionsTensorList")
        x = inp("X")
        axis = a.get("axis", 0) or 0
        sections = a.get("sections") or []
        num = a.get("num", 0) or 0
        if num:
            pieces = jnp.split(x, num, axis=axis)
        else:
            sections = [int(s) for s in sections]
            if -1 in sections:
                known = sum(s for s in sections if s != -1)
                sections = [x.shape[axis] - known if s == -1 else s
                            for s in sections]
            pieces = jnp.split(x, np.cumsum(sections[:-1]).tolist(),
                               axis=axis)
        for i, p in enumerate(pieces):
            set_out("Out", p, idx=i)
    elif t in ("slice", "strided_slice"):
        no_tensor_operands("StartsTensor", "EndsTensor", "StridesTensor",
                           "StartsTensorList", "EndsTensorList",
                           "StridesTensorList")
        x = inp("Input")
        axes = [int(v) for v in (a.get("axes") or [])]
        starts = [int(v) for v in (a.get("starts") or [])]
        ends = [int(v) for v in (a.get("ends") or [])]
        strides = [int(v) for v in (a.get("strides") or [1] * len(axes))]
        idx = [slice(None)] * x.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax] = slice(st, en, sd)
        y = x[tuple(idx)]
        decrease = a.get("decrease_axis") or []
        if decrease:
            y = jnp.squeeze(y, axis=tuple(int(d) for d in decrease))
        set_out("Out", y)
    elif t in ("unsqueeze2", "unsqueeze"):
        no_tensor_operands("AxesTensor", "AxesTensorList")
        x = inp("X")
        # reference kernel inserts axes SEQUENTIALLY in the given order
        # (each insertion sees the previous one's shape) — not sorted
        for ax in (int(v) for v in (a.get("axes") or [])):
            x = jnp.expand_dims(x, ax if ax >= 0 else ax + x.ndim + 1)
        set_out("Out", x)
    elif t in ("squeeze2", "squeeze"):
        axes = [int(v) for v in (a.get("axes") or [])]
        x = inp("X")
        if axes:
            set_out("Out", jnp.squeeze(x, axis=tuple(
                ax if ax >= 0 else ax + x.ndim for ax in axes)))
        else:
            set_out("Out", jnp.squeeze(x))
    elif t == "cast":
        set_out("Out", inp("X").astype(
            _dtype_of(a.get("out_dtype", 5))))
    elif t == "gather":
        axis = inp("Axis")
        axis = int(axis) if axis is not None else a.get("axis", 0) or 0
        set_out("Out", jnp.take(inp("X"),
                                inp("Index").astype(jnp.int32),
                                axis=axis))
    elif t == "expand_v2":
        no_tensor_operands("Shape", "expand_shapes_tensor")
        x = inp("X")
        shape = [int(s) for s in (a.get("shape") or [])]
        lead = len(shape) - x.ndim
        full = [shape[i] if shape[i] != -1
                else (x.shape[i - lead] if i >= lead else 1)
                for i in range(len(shape))]
        set_out("Out", jnp.broadcast_to(x, full))
    elif t in ("reduce_mean", "reduce_sum", "reduce_max", "reduce_min"):
        x = inp("X")
        fns = {"reduce_mean": jnp.mean, "reduce_sum": jnp.sum,
               "reduce_max": jnp.max, "reduce_min": jnp.min}
        dims = a.get("dim") or []
        axis = None if (a.get("reduce_all") or not dims) \
            else tuple(int(d) for d in dims)
        set_out("Out", fns[t](x, axis=axis,
                              keepdims=bool(a.get("keep_dim"))))
    elif t == "sqrt":
        set_out("Out", jnp.sqrt(inp("X")))
    elif t == "square":
        set_out("Out", jnp.square(inp("X")))
    elif t == "exp":
        set_out("Out", jnp.exp(inp("X")))
    elif t == "log":
        set_out("Out", jnp.log(inp("X")))
    elif t in ("silu", "swish"):
        set_out("Out", jax.nn.silu(inp("X")))
    else:
        raise UnimplementedError(
            "reference-model importer: op %r is not in the supported "
            "inference subset" % t,
            hint="extend paddle_tpu/static/ref_import.py:_run_op or "
                 "re-export the model without this op")


class ReferenceInferenceModel:
    """Callable imported model: feed dict -> fetch list."""

    def __init__(self, program, params):
        import jax.numpy as jnp

        self.program = program
        block = program.blocks[0]
        self.feed_names = []
        self.fetch_names = []
        for op in block["ops"]:
            if op.type == "feed":
                self.feed_names.append(op.outputs["Out"][0])
            elif op.type == "fetch":
                self.fetch_names.append(op.inputs["X"][0])
        self.params = {k: jnp.asarray(v) for k, v in params.items()}

    def run(self, feeds):
        import jax.numpy as jnp

        env = dict(self.params)
        for k, v in feeds.items():
            env[k] = jnp.asarray(v)
        for op in self.program.blocks[0]["ops"]:
            _run_op(op, env)
        return [env[n] for n in self.fetch_names]

    def __call__(self, *inputs):
        return self.run(dict(zip(self.feed_names, inputs)))


def load_reference_inference_model(path_prefix):
    """Import `<prefix>.pdmodel` + `<prefix>.pdiparams` (or the legacy
    `__model__` + `__params__` pair) saved by the reference framework."""
    import os

    if os.path.isdir(path_prefix):
        model_path = os.path.join(path_prefix, "__model__")
        params_path = os.path.join(path_prefix, "__params__")
    else:
        model_path = path_prefix + ".pdmodel"
        params_path = path_prefix + ".pdiparams"
    with open(model_path, "rb") as f:
        program = ProgramDesc(f.read())
    persistable = sorted(
        v.name for v in program.blocks[0]["vars"]
        if v.persistable and v.name not in ("feed", "fetch"))
    params = {}
    if persistable:
        params = read_combined_params(params_path, persistable)
    return ReferenceInferenceModel(program, params)


def is_reference_format(path_prefix):
    """ProgramDesc protobuf starts with the blocks field tag (0x0a);
    this framework's own .pdmodel artifacts are pickles (0x80...)."""
    import os

    for cand in (path_prefix + ".pdmodel",
                 os.path.join(path_prefix, "__model__")
                 if os.path.isdir(path_prefix) else path_prefix):
        try:
            with open(cand, "rb") as f:
                return f.read(1) == b"\n"
        except OSError:
            continue
    return False
