"""static.nn builder completions.

Parity: reference python/paddle/static/nn/__init__.py — the fluid-era
graph builders (conv/norm families, sequence_* ops, StaticRNN, nce,
row_conv). Conventions:

- builders create their own parameters (reference behavior) via
  paddle.create_parameter and delegate math to the shared ops/F bodies;
- the sequence_* family operated on LoDTensor; the TPU convention is
  padded [B, T, ...] plus an explicit `lengths` tensor (SURVEY §7 "hard
  parts": LoD → padding/bucketing). Each op documents its mapping; ops
  whose output is ragged return the packed [sum(len), ...] form.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "conv2d", "conv3d", "conv2d_transpose", "conv3d_transpose",
    "deform_conv2d", "layer_norm", "group_norm", "instance_norm",
    "data_norm", "spectral_norm", "prelu", "bilinear_tensor_product",
    "nce", "row_conv", "StaticRNN", "py_func", "sparse_embedding",
    "sequence_concat", "sequence_conv", "sequence_enumerate",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_pad", "sequence_pool",
    "sequence_reshape", "sequence_reverse", "sequence_scatter",
    "sequence_slice", "sequence_softmax", "sequence_unpad",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _P():
    import paddle_tpu as P

    return P


# -- parameterized builders --------------------------------------------------

def _act(out, act):
    if act:
        import paddle_tpu.nn.functional as F

        return getattr(F, act)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    """reference static.nn.conv2d."""
    import paddle_tpu.nn.functional as F

    P = _P()
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 2
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = P.create_parameter([num_filters, cin // groups] + list(ks))
    b = None if bias_attr is False else P.create_parameter([num_filters])
    out = F.conv2d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCDHW"):
    """reference static.nn.conv3d."""
    import paddle_tpu.nn.functional as F

    P = _P()
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    w = P.create_parameter([num_filters, cin // groups] + list(ks))
    b = None if bias_attr is False else P.create_parameter([num_filters])
    out = F.conv3d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    return _act(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCHW"):
    """reference static.nn.conv2d_transpose."""
    import paddle_tpu.nn.functional as F

    P = _P()
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 2
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = P.create_parameter([cin, num_filters // groups] + list(ks))
    b = None if bias_attr is False else P.create_parameter([num_filters])
    out = F.conv2d_transpose(input, w, bias=b, stride=stride,
                             padding=padding, dilation=dilation,
                             groups=groups, output_size=output_size,
                             data_format=data_format)
    return _act(out, act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCDHW"):
    """reference static.nn.conv3d_transpose."""
    import paddle_tpu.nn.functional as F

    P = _P()
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    w = P.create_parameter([cin, num_filters // groups] + list(ks))
    b = None if bias_attr is False else P.create_parameter([num_filters])
    out = F.conv3d_transpose(input, w, bias=b, stride=stride,
                             padding=padding, dilation=dilation,
                             groups=groups, output_size=output_size,
                             data_format=data_format)
    return _act(out, act)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    """reference static.nn.deform_conv2d (v2 with mask)."""
    import paddle_tpu.nn.functional as F

    P = _P()
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 2
    cin = input.shape[1]
    w = P.create_parameter([num_filters, cin // groups] + list(ks))
    b = None if bias_attr is False else P.create_parameter([num_filters])
    return F.deformable_conv(input, offset, w, mask=mask, bias=b,
                             stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             deformable_groups=deformable_groups)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference static.nn.layer_norm: normalize over dims
    [begin_norm_axis:]."""
    import paddle_tpu.nn.functional as F

    P = _P()
    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    w = P.create_parameter(shape,
                           default_initializer=None) if scale else None
    if w is not None:
        w._value = jnp.ones(shape, _v(input).dtype)
    b = P.create_parameter(shape) if shift else None
    if b is not None:
        b._value = jnp.zeros(shape, _v(input).dtype)
    out = F.layer_norm(input, shape, weight=w, bias=b, epsilon=epsilon)
    return _act(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    """reference static.nn.group_norm."""
    import paddle_tpu.nn as nn

    gn = nn.GroupNorm(groups, input.shape[1], epsilon=epsilon)
    return _act(gn(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    """reference static.nn.instance_norm."""
    import paddle_tpu.nn as nn

    inorm = nn.InstanceNorm2D(input.shape[1], epsilon=epsilon)
    return inorm(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """reference static.nn.data_norm: normalization by RUNNING batch
    statistics without learned scale/shift (CTR models); here the batch
    statistics themselves (single-pass form)."""
    v = _v(input)
    mean = v.mean(axis=0, keepdims=True)
    var = v.var(axis=0, keepdims=True)
    out = (v - mean) / jnp.sqrt(var + epsilon)
    return _act(Tensor(out), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference static.nn.spectral_norm: w / sigma_max(w) via power
    iteration."""
    w = _v(weight)
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    u = jnp.ones((mat.shape[0],), mat.dtype) / np.sqrt(mat.shape[0])
    for _ in range(max(power_iters, 1)):
        vvec = mat.T @ u
        vvec = vvec / (jnp.linalg.norm(vvec) + eps)
        u = mat @ vvec
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ mat @ vvec
    return Tensor(w / (sigma + eps))


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    """reference static.nn.prelu: modes all/channel/element."""
    import paddle_tpu.nn.functional as F

    P = _P()
    if mode == "all":
        shape = [1]
    elif mode == "channel":
        shape = [x.shape[1] if data_format == "NCHW" else x.shape[-1]]
    else:
        shape = [int(s) for s in x.shape[1:]]
    alpha = P.create_parameter(shape)
    alpha._value = jnp.full(shape, 0.25, _v(x).dtype)
    return F.prelu(x, alpha)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference static.nn.bilinear_tensor_product."""
    import paddle_tpu.nn.functional as F

    P = _P()
    w = P.create_parameter([size, x.shape[1], y.shape[1]])
    b = None if bias_attr is False else P.create_parameter([size])
    return _act(F.bilinear(x, y, w, bias=b), act)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32"):
    """reference static.nn.sparse_embedding (PS path): on the TPU stack
    the PS-backed lookup is fleet.utils DistributedInfer's _PSEmbedding;
    locally this is a plain embedding table."""
    import paddle_tpu.nn.functional as F

    P = _P()
    w = P.create_parameter(list(size), dtype=dtype)
    return F.embedding(input, w, padding_idx=padding_idx)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=5, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference static.nn.nce):
    binary logistic over the true class + sampled negatives (uniform or
    custom sampler)."""
    P = _P()
    x = _v(input)
    lbl = _v(label).astype(jnp.int32).reshape(-1)
    n, d = x.shape
    w = P.create_parameter([num_total_classes, d])
    b = P.create_parameter([num_total_classes])
    from ..framework import random as _random

    key = _random.next_key()
    if sampler == "custom_dist" and custom_dist is not None:
        probs = jnp.asarray(custom_dist)
        neg = jax.random.choice(key, num_total_classes,
                                (n, num_neg_samples), p=probs)
    else:
        neg = jax.random.randint(key, (n, num_neg_samples), 0,
                                 num_total_classes)
    wv, bv = _v(w), _v(b)
    pos_logit = jnp.einsum("nd,nd->n", x, wv[lbl]) + bv[lbl]
    neg_logit = jnp.einsum("nd,nkd->nk", x, wv[neg]) + bv[neg]
    pos_loss = -jax.nn.log_sigmoid(pos_logit)
    neg_loss = -jax.nn.log_sigmoid(-neg_logit).sum(axis=1)
    return Tensor((pos_loss + neg_loss)[:, None])


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference static.nn.row_conv /
    phi row_conv_kernel): out[t] = sum_{i=0..C} w[i] * x[t+i]."""
    P = _P()
    x = _v(input)  # [B, T, D]
    C = future_context_size
    w = P.create_parameter([C + 1, x.shape[-1]])
    wv = _v(w)
    pad = jnp.pad(x, ((0, 0), (0, C), (0, 0)))
    out = sum(pad[:, i:i + x.shape[1], :] * wv[i] for i in range(C + 1))
    return _act(Tensor(out), act)


def py_func(func, x, out=None, backward_func=None,
            skip_vars_in_backward_input=None):
    from .extras import py_func as _pf

    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


# -- StaticRNN ---------------------------------------------------------------

class StaticRNN:
    """reference static.nn.StaticRNN — an explicitly-stepped RNN block.

    The fluid form records a step BLOCK into the ProgramDesc
    (rnn.step_input/memory/update_memory inside `with rnn.step()`); that
    block-capture machinery is ProgramDesc-specific, so this stack
    provides the equivalent functional form instead:

        out, final_states = StaticRNN.scan(step_fn, x, init_states)

    where step_fn(x_t, states) -> (out_t, new_states) and x is
    [B, T, ...]. Under the tape Program the whole replay jits into one
    XLA module, same as the reference's unrolled block. Constructing the
    fluid block form raises with this guidance.
    """

    def __init__(self, name=None):
        raise RuntimeError(
            "StaticRNN block-capture needs ProgramDesc blocks; use the "
            "functional form: StaticRNN.scan(step_fn, inputs, "
            "init_states) (see docstring)")

    @staticmethod
    def scan(step_fn, inputs, init_states):
        """Functional StaticRNN: step_fn(x_t, states) -> (out_t, states);
        inputs [B, T, ...] -> outputs [B, T, ...]."""
        x = _v(inputs)
        T = x.shape[1]
        states = init_states
        outs = []
        for t in range(T):
            out_t, states = step_fn(Tensor(x[:, t]), states)
            outs.append(_v(out_t))
        return Tensor(jnp.stack(outs, axis=1)), states


# -- sequence ops over (padded, lengths) -------------------------------------

def _lens(lengths, batch):
    if lengths is None:
        raise ValueError(
            "sequence ops on the TPU stack take explicit `lengths` "
            "(LoD -> padded+lengths convention, SURVEY §7)")
    return _v(lengths).astype(jnp.int32).reshape(batch)


def _time_mask(lengths, T):
    return jnp.arange(T)[None, :] < lengths[:, None]


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Packed [sum(len), ...] + lengths -> (padded [B, maxlen, ...],
    lengths) (reference sequence_pad over LoD input)."""
    v = _v(x)
    lens = _v(length).astype(jnp.int32).reshape(-1)
    B = lens.shape[0]
    T = int(maxlen) if maxlen else int(np.asarray(lens).max())
    offs = np.concatenate([[0], np.cumsum(np.asarray(lens))])
    rows = []
    pv = _v(pad_value)
    for b in range(B):
        seg = v[int(offs[b]):int(offs[b + 1])]
        padn = T - seg.shape[0]
        fill = jnp.broadcast_to(pv, (padn,) + seg.shape[1:]) \
            if padn > 0 else seg[:0]
        rows.append(jnp.concatenate([seg, fill], axis=0))
    return Tensor(jnp.stack(rows)), Tensor(lens)


def sequence_unpad(x, length, name=None):
    """(padded [B, T, ...], lengths) -> packed [sum(len), ...]
    (reference sequence_unpad)."""
    v = _v(x)
    lens = _lens(length, v.shape[0])
    segs = [v[b, :int(lens[b])] for b in range(v.shape[0])]
    return Tensor(jnp.concatenate(segs, axis=0))


def sequence_concat(input, name=None, lengths=None):
    """Concatenate per-row sequences time-wise (reference
    sequence_concat over LoD): list of (padded, lengths) pairs when
    `lengths` given, else plain time-axis concat."""
    if lengths is None:
        return Tensor(jnp.concatenate([_v(i) for i in input], axis=1))
    parts = []
    B = _v(input[0]).shape[0]
    lens = [_lens(l, B) for l in lengths]
    rows = []
    for b in range(B):
        segs = [_v(x)[b, :int(l[b])] for x, l in zip(input, lens)]
        rows.append(jnp.concatenate(segs, axis=0))
    T = max(r.shape[0] for r in rows)
    padded = [jnp.pad(r, ((0, T - r.shape[0]),) + ((0, 0),) * (r.ndim - 1))
              for r in rows]
    total = sum(lens)
    return Tensor(jnp.stack(padded)), Tensor(total)


def sequence_first_step(input, lengths=None, name=None):
    """reference sequence_first_step: x[:, 0] of each valid sequence."""
    return Tensor(_v(input)[:, 0])


def sequence_last_step(input, lengths=None, name=None):
    """reference sequence_last_step: the last VALID step per row."""
    v = _v(input)
    lens = _lens(lengths, v.shape[0])
    idx = jnp.maximum(lens - 1, 0)
    return Tensor(v[jnp.arange(v.shape[0]), idx])


def sequence_pool(input, pool_type, lengths=None, is_test=False,
                  pad_value=0.0):
    """reference sequence_pool: sum/average/sqrt/max/last/first over the
    valid steps."""
    v = _v(input)
    lens = _lens(lengths, v.shape[0])
    mask = _time_mask(lens, v.shape[1])
    m = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
    pool = pool_type.lower()
    if pool == "max":
        filled = jnp.where(m, v, -jnp.inf)
        out = filled.max(axis=1)
        return Tensor(jnp.where(jnp.isfinite(out), out, pad_value))
    if pool == "last":
        return sequence_last_step(input, lengths)
    if pool == "first":
        return sequence_first_step(input, lengths)
    s = jnp.where(m, v, 0.0).sum(axis=1)
    denom = jnp.maximum(lens, 1).reshape((-1,) + (1,) * (v.ndim - 2))
    if pool == "average":
        return Tensor(s / denom)
    if pool == "sqrt":
        return Tensor(s / jnp.sqrt(denom.astype(s.dtype)))
    return Tensor(s)  # sum


def sequence_softmax(input, lengths=None, use_cudnn=False, name=None):
    """reference sequence_softmax: softmax over each row's valid prefix."""
    v = _v(input)
    lens = _lens(lengths, v.shape[0])
    mask = _time_mask(lens, v.shape[1])
    m = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
    logits = jnp.where(m, v, -1e30)
    return Tensor(jax.nn.softmax(logits, axis=1) * m)


def sequence_reverse(x, lengths=None, name=None):
    """reference sequence_reverse: flip each valid prefix, keep padding."""
    v = _v(x)
    lens = _lens(lengths, v.shape[0])
    T = v.shape[1]
    pos = jnp.arange(T)[None, :]
    src = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
    return Tensor(jnp.take_along_axis(
        v, src.reshape(src.shape + (1,) * (v.ndim - 2)), axis=1))


def sequence_enumerate(input, win_size, pad_value=0, name=None,
                       lengths=None):
    """reference sequence_enumerate: sliding win_size windows per step,
    padded past each row's valid length."""
    v = _v(input)
    B, T = v.shape[:2]
    lens = _lens(lengths, B) if lengths is not None \
        else jnp.full((B,), T, jnp.int32)
    cols = []
    for k in range(win_size):
        pos = jnp.arange(T) + k
        valid = pos[None, :] < lens[:, None]
        gathered = jnp.take(v, jnp.minimum(pos, T - 1), axis=1)
        cols.append(jnp.where(valid, gathered, pad_value))
    return Tensor(jnp.stack(cols, axis=-1))


def sequence_expand(x, y, ref_level=-1, name=None, repeats=None):
    """reference sequence_expand: repeat each row per the ref sequence's
    LoD. TPU form: explicit `repeats` [B] ints."""
    if repeats is None:
        raise ValueError(
            "sequence_expand needs explicit `repeats` (the LoD of y)")
    v = _v(x)
    r = np.asarray(_v(repeats)).astype(np.int64)
    return Tensor(jnp.repeat(v, jnp.asarray(r), axis=0,
                             total_repeat_length=int(r.sum())))


def sequence_expand_as(x, y, name=None, repeats=None):
    return sequence_expand(x, y, repeats=repeats)


def sequence_reshape(input, new_dim):
    """reference sequence_reshape: refold the trailing dim of a packed
    sequence."""
    v = _v(input)
    total = v.shape[0] * v.shape[-1]
    return Tensor(v.reshape(total // new_dim, new_dim))


def sequence_scatter(input, index, updates, name=None):
    """reference sequence_scatter: add updates at (row, position) pairs;
    index packs positions per row ([n, 2] int (row, pos))."""
    v = _v(input)
    idx = _v(index).astype(jnp.int32)
    upd = _v(updates)
    return Tensor(v.at[idx[:, 0], idx[:, 1]].add(upd))


def sequence_slice(input, offset, length, name=None):
    """reference sequence_slice: per-row [offset, offset+length) windows
    -> padded to max(length)."""
    v = _v(input)
    off = np.asarray(_v(offset)).reshape(-1).astype(np.int64)
    ln = np.asarray(_v(length)).reshape(-1).astype(np.int64)
    T = int(ln.max()) if len(ln) else 0
    rows = []
    for b in range(v.shape[0]):
        seg = v[b, int(off[b]):int(off[b] + ln[b])]
        rows.append(jnp.pad(
            seg, ((0, T - seg.shape[0]),) + ((0, 0),) * (seg.ndim - 1)))
    return Tensor(jnp.stack(rows)), Tensor(jnp.asarray(ln, jnp.int32))


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """reference sequence_conv: temporal context conv over [B, T, D]."""
    P = _P()
    x = _v(input)
    D = x.shape[-1]
    w = P.create_parameter([filter_size * D, num_filters])
    start = -((filter_size - 1) // 2) if padding_start is None \
        else padding_start
    ctx = []
    T = x.shape[1]
    for k in range(filter_size):
        shift = start + k
        pos = jnp.clip(jnp.arange(T) + shift, 0, T - 1)
        col = jnp.take(x, pos, axis=1)
        valid = (jnp.arange(T) + shift >= 0) & (jnp.arange(T) + shift < T)
        ctx.append(jnp.where(valid[None, :, None], col, 0.0))
    stacked = jnp.concatenate(ctx, axis=-1)         # [B, T, k*D]
    out = stacked @ _v(w)
    if bias_attr is not False:
        b = P.create_parameter([num_filters])
        out = out + _v(b)
    return _act(Tensor(out), act)
