"""paddle.static — whole-graph capture & execution, TPU-native.

Reference design: ProgramDesc protobuf + Executor/InterpreterCore
(/root/reference/paddle/fluid/framework/framework.proto:242,
 new_executor/standalone_executor.h:32, python/paddle/fluid/executor.py:921).

TPU-native design: a Program is an **op tape** — while `program_guard` is
active, every eager op executed through the dispatch layer is appended to the
tape (dispatch.set_static_recorder). `Executor.run(feed, fetch_list)` replays
the tape as a pure function of the feed values and captured parameters under
`jax.jit`, producing ONE XLA module per feed signature — what the reference's
InterpreterCore + paddle2cinn pipeline approximates with per-op dispatch and
subgraph compilation, done structurally here. `Optimizer.minimize(loss)`
inside a program records a training spec; the Executor then compiles
forward+backward+update into a single donated XLA module (the analog of the
reference's append_backward + optimizer-op insertion, with XLA autodiff
replacing per-op GradOpMakers).

State semantics match the reference executor: Tensor.set_value(Tensor)
during capture registers a STATE EDGE (`_record_state_assign`) — BatchNorm
running stats and other mutated buffers are threaded out of the compiled
module and written back after every run (the reference batch_norm op's
MeanOut/VarianceOut). RNG ops draw from a fresh per-run key passed as a
traced argument (framework.random.set_replay_base), so dropout masks
differ across Executor.run calls exactly as in dygraph.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch as _dispatch
from ..core import dtype as _dtype
from ..core.tensor import Parameter, Tensor
from ..jit import InputSpec  # noqa: F401

_state = threading.local()


def _enabled():
    return getattr(_state, "static_mode", False)


def enable_static():
    _state.static_mode = True
    _install_recorder()


def disable_static():
    _state.static_mode = False
    _dispatch.set_static_recorder(None)


def in_dynamic_mode():
    return not _enabled()


class Variable(Tensor):
    """Feed placeholder in a Program (reference VarDesc). Holds a zero value
    of the spec'd shape at build time; bound to real feeds at Executor.run."""

    def __init__(self, name, shape, dtype):
        super().__init__(jnp.zeros([1 if s in (-1, None) else s
                                    for s in shape],
                                   _dtype.to_jax(dtype)))
        self.name = name
        self.spec_shape = list(shape)
        self.is_data = True


class _OpRecord:
    __slots__ = ("op_name", "raw_fn", "leaves", "treedef", "outs", "multi")

    def __init__(self, op_name, raw_fn, leaves, treedef, outs, multi):
        self.op_name = op_name
        self.raw_fn = raw_fn
        self.leaves = leaves      # mixed list; Tensor refs read live at replay
        self.treedef = treedef
        self.outs = outs          # tuple[Tensor]
        self.multi = multi


class Program:
    """Captured computation (reference ProgramDesc)."""

    def __init__(self):
        self.feed_vars = {}
        self.tape = []            # list[_OpRecord]
        self.version = 0
        self._train_spec = None   # (loss Tensor, Optimizer)
        self._grad_map = {}       # id(param) -> grad placeholder Tensor
        self._opt_state = None
        self._run_cache = {}
        self._analyze_cache = None  # (version, params, frozen)
        self._state_updates = {}  # id(target) -> (target, source Tensor)
        self._tape_out_ids = set()  # ids of tensors produced by the tape

    # -- introspection (reference Program API) ---------------------------
    def global_block(self):
        return self

    @property
    def ops(self):
        return self.tape

    def clone(self, for_test=False):
        p = Program.__new__(Program)
        p.__dict__.update(self.__dict__)
        p.tape = list(self.tape)
        p.feed_vars = dict(self.feed_vars)
        p._grad_map = dict(self._grad_map)
        p._state_updates = dict(self._state_updates)
        p._tape_out_ids = set(self._tape_out_ids)
        p._run_cache = {}
        p._analyze_cache = None
        p.__dict__.pop("_native_interp", None)  # DAG is per-program
        if for_test:
            p._train_spec = None
            # reference clone(for_test=True) -> _inference_optimize:
            # dropout becomes identity, batch_norm switches to running
            # stats (is_test=1 on the ops). Without this the cloned
            # program would stay stochastic / keep batch statistics.
            from ..distributed.passes import new_pass

            new_pass("set_is_test").apply(p)
        return p

    def var(self, name):
        return self.feed_vars.get(name)

    def list_vars(self):
        return list(self.feed_vars.values())

    def _bump(self):
        self.version += 1
        self._run_cache.clear()

    # -- tape analysis ---------------------------------------------------
    def _analyze(self):
        cached = self._analyze_cache
        if cached is not None and cached[0] == self.version:
            return cached[1], cached[2]
        params, frozen = self._analyze_impl()
        self._analyze_cache = (self.version, params, frozen)
        return params, frozen

    def _analyze_impl(self):
        produced = set()
        for rec in self.tape:
            for t in rec.outs:
                produced.add(id(t))
        feed_ids = {id(v) for v in self.feed_vars.values()}
        captured, seen = [], set()
        for rec in self.tape:
            for l in rec.leaves:
                if isinstance(l, Tensor) and id(l) not in produced \
                        and id(l) not in feed_ids and id(l) not in seen:
                    seen.add(id(l))
                    captured.append(l)
        ts = self._train_spec
        opt_params = None
        if ts is not None and ts[1] is not None:
            try:
                opt_params = {id(p) for p in ts[1]._get_params()}
            except ValueError:
                pass  # static-graph optimizers may omit the parameter list
        params = [t for t in captured
                  if isinstance(t, Parameter) or not t.stop_gradient]
        if opt_params is not None:
            params = [t for t in params if id(t) in opt_params] or params
        frozen = [t for t in captured if not any(t is p for p in params)]
        return params, frozen


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return getattr(_state, "main_program", _default_main)


def default_startup_program():
    return getattr(_state, "startup_program", _default_startup)


def _recording_program():
    if not _enabled():
        return None
    return getattr(_state, "main_program", None) or _default_main


def _record(op_name, raw_fn, leaves, treedef, outs, multi):
    prog = _recording_program()
    if prog is None:
        return
    prog.tape.append(_OpRecord(op_name, raw_fn, leaves, treedef, outs, multi))
    for t in outs:
        prog._tape_out_ids.add(id(t))
    prog._bump()


def _record_state_assign(target, source):
    """Tensor.set_value(Tensor) during capture = a state edge: Executor
    threads `source`'s replayed value back into `target` after each run
    (BatchNorm running stats; the reference batch_norm op's
    MeanOut/VarianceOut outputs).

    Only assignments whose SOURCE was produced on this program's tape are
    state edges; unrelated copies (weight loading, layer conversion)
    execute eagerly as usual (return False)."""
    prog = _recording_program()
    if prog is None or id(source) not in prog._tape_out_ids:
        return False
    prog._state_updates[id(target)] = (target, source)
    prog._bump()
    return True


def _install_recorder():
    _dispatch.set_static_recorder(_record, _record_state_assign)


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        self._prev = (getattr(_state, "main_program", None),
                      getattr(_state, "startup_program", None))
        _state.main_program = self.main
        _state.startup_program = self.startup or _default_startup
        if _enabled():
            _install_recorder()
        return self

    def __exit__(self, *a):
        _state.main_program, _state.startup_program = self._prev
        return False


def data(name, shape, dtype="float32", lod_level=0):
    v = Variable(name, shape, dtype)
    default_main_program().feed_vars[name] = v
    return v


def _register_minimize(loss, optimizer):
    """Called by Optimizer.minimize under static recording: record the
    training spec instead of running eager backward (reference: optimizer
    ops appended to the ProgramDesc by minimize)."""
    prog = _recording_program()
    if prog is None:
        return False
    prog._train_spec = (loss, optimizer)
    prog._bump()
    return True


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Record gradient computation; returns [(param, grad_placeholder)].

    Reference python/paddle/fluid/backward.py:1729. Gradients are computed by
    XLA autodiff over the replayed tape at Executor.run; the placeholders
    returned here are fetchable."""
    prog = _recording_program() or default_main_program()
    params, _ = prog._analyze()
    if parameter_list:
        params = list(parameter_list)
    out = []
    for p in params:
        g = Tensor(jnp.zeros_like(p._value))
        g.name = (getattr(p, "name", None) or "param") + "@GRAD"
        prog._grad_map[id(p)] = g
        out.append((p, g))
    if prog._train_spec is None:
        prog._train_spec = (loss, None)
    prog._bump()
    return out


class _ReplayContext:
    """Snapshot/restore of every Tensor the tape touches, so replaying under
    a jax trace (mutating ._value to tracers) leaves build-time state
    intact."""

    def __init__(self, program, extra=()):
        tensors = {}
        for rec in program.tape:
            for l in rec.leaves:
                if isinstance(l, Tensor):
                    tensors[id(l)] = l
            for t in rec.outs:
                tensors[id(t)] = t
        for v in program.feed_vars.values():
            tensors[id(v)] = v
        for t in extra:
            tensors[id(t)] = t
        for g in program._grad_map.values():
            tensors[id(g)] = g
        self.tensors = list(tensors.values())

    def __enter__(self):
        self._saved = [t._value for t in self.tensors]
        return self

    def __exit__(self, *a):
        for t, v in zip(self.tensors, self._saved):
            t._value = v
        return False


def _run_tape_recompute(program, segments):
    """Replay the tape as checkpoint-delimited segments, each under
    jax.checkpoint: activations between checkpoints are rematerialized
    in backward instead of saved (the auto_parallel_recompute pass;
    reference recompute clones forward subgraphs into the grad block).
    """
    from ..core.interpreter import replay_record

    tape = program.tape
    keep_ids = getattr(program, "_replay_keep_ids", set())

    _dispatch._enter_primitive()
    try:
        for si, (s, e) in enumerate(segments):
            seg = tape[s:e]
            produced = {id(t) for rec in seg for t in rec.outs}
            # explicit inputs: every Tensor leaf not produced inside —
            # params included, so remat recomputes w.r.t. them (a
            # closed-over param would be a non-differentiable residual)
            ins, seen = [], set()
            for rec in seg:
                for l in rec.leaves:
                    if isinstance(l, Tensor) and id(l) not in produced \
                            and id(l) not in seen:
                        seen.add(id(l))
                        ins.append(l)
            # explicit outputs: consumed by later segments, or kept
            # (fetches / loss / state sources), or checkpoint-final
            later_consumed = set()
            for rec in tape[e:]:
                for l in rec.leaves:
                    if isinstance(l, Tensor):
                        later_consumed.add(id(l))
            outs, oseen = [], set()
            for rec in seg:
                for t in rec.outs:
                    if id(t) in oseen:
                        continue
                    if (id(t) in later_consumed or id(t) in keep_ids
                            or (si == len(segments) - 1
                                and rec is seg[-1])):
                        oseen.add(id(t))
                        outs.append(t)

            def seg_fn(*invals, _seg=seg, _ins=ins, _outs=outs):
                for t, v in zip(_ins, invals):
                    t._value = v
                for rec in _seg:
                    replay_record(rec)
                return tuple(t._value for t in _outs)

            vals = jax.checkpoint(seg_fn)(*[t._value for t in ins])
            for t, v in zip(outs, vals):
                t._value = v
    finally:
        _dispatch._exit_primitive()


def _run_tape(program):
    """Un-jitted replay. Prefers the native C++ interpreter (csrc/interp.cc
    — dependency-counted workqueue, the reference InterpreterCore analog);
    falls back to sequential Python replay if the native core is
    unavailable. Toggle with FLAGS_use_native_interpreter."""
    from ..core import flags as _flags

    segments = getattr(program, "_recompute_segments", None)
    if segments and len(segments) > 1:
        return _run_tape_recompute(program, segments)

    # ptlint: compile-discipline-ok — the flag picks HOW the tape is
    # replayed (native vs python driver) while building the graph; it
    # is a per-compile host decision, never a value baked into the
    # compiled program
    use_native = _flags.get_flags().get("FLAGS_use_native_interpreter", True)
    if use_native and program.tape:
        try:
            interp = program._native_interp
        except AttributeError:
            interp = None
        if interp is None or interp._version != program.version:
            try:
                from ..core.interpreter import NativeInterpreter

                interp = NativeInterpreter(program)
                interp._version = program.version
                program._native_interp = interp
            except Exception:
                # ptlint: compile-discipline-ok — verbosity check on the
                # native-interpreter fallback path; trace-time diagnostic
                # only, nothing graph-visible depends on it
                if _flags.get_flags().get("FLAGS_v", 0) > 0:
                    import traceback

                    traceback.print_exc()
                interp = None
        if interp is not None:
            interp.run()
            return
    from ..core.interpreter import replay_record

    _dispatch._enter_primitive()
    try:
        for rec in program.tape:
            replay_record(rec)
    finally:
        _dispatch._exit_primitive()


def _fetch_tensor(program, f):
    if isinstance(f, Tensor):
        return f
    t = program.var(str(f))
    if t is None:
        raise KeyError("fetch target %r not found in program" % (f,))
    return t


def _as_program(program):
    """Normalize run()/dataset entry points' program argument: a Program,
    a CompiledProgram wrapper, or None (-> default main)."""
    if isinstance(program, Program):
        return program
    return getattr(program, "program", None) or default_main_program()


class Executor:
    """reference python/paddle/fluid/executor.py:921 + StandaloneExecutor.

    run() replays the program tape under jax.jit — one compiled XLA module
    per (program version, feed signature, fetch set); training programs
    compile forward+grad+update into one donated module."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, use_program_cache=True, scope=None):
        # use_program_cache: accepted for reference API parity; programs
        # are always cached per (version, feed signature) here.
        # scope: reference executor.py run(scope=) — program state
        # (params, BN stats, optimizer slots) lives in the scope, so the
        # same Program trains independently under different scopes.
        if isinstance(program, InferenceProgram):
            feed = feed or {}
            outs = program.run(*[feed[n] for n in program.feed_names])
            return [np.asarray(o) for o in outs] if return_numpy \
                else [Tensor(o) for o in outs]
        from .ref_import import ReferenceInferenceModel

        if isinstance(program, ReferenceInferenceModel):
            # reference-format import (ref_import.py): same exe.run
            # contract as the reference's serving flow
            outs = program.run(feed or {})
            return [np.asarray(o) for o in outs] if return_numpy \
                else [Tensor(o) for o in outs]
        program = _as_program(program)
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if not program.tape and not program.feed_vars:
            return []  # startup program: params initialize eagerly
        missing = sorted(program.feed_vars.keys() - feed.keys())
        unknown = sorted(feed.keys() - program.feed_vars.keys())
        if missing:
            raise ValueError(
                "Executor.run: program feed vars %s were not fed "
                "(got feeds %s)" % (missing, sorted(feed.keys())))
        if unknown:
            raise ValueError(
                "Executor.run: feed keys %s match no program feed var "
                "(program has %s)" % (
                    unknown, sorted(program.feed_vars.keys())))
        feed_names = sorted(program.feed_vars.keys())
        feed_tensors = [program.feed_vars[n] for n in feed_names]
        feed_vals = [jnp.asarray(np.asarray(feed[n])) for n in feed_names]
        fetch_tensors = [_fetch_tensor(program, f) for f in fetch_list]
        params, frozen = program._analyze()

        key = (program.version, tuple(feed_names),
               tuple((v.shape, str(v.dtype)) for v in feed_vals),
               tuple(id(t) for t in fetch_tensors))
        entry = program._run_cache.get(key)
        if entry is None:
            entry = self._compile(program, feed_tensors, fetch_tensors,
                                  params, frozen)
            program._run_cache[key] = entry
        outs = self._run_in_scope(entry, program, feed_vals, params,
                                  frozen, scope)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _run_in_scope(self, entry, program, feed_vals, params, frozen,
                      scope):
        """Route program state through the target scope (reference
        framework/scope.h: the Executor reads/creates variables in the
        scope it runs against).

        The base global scope is backed by the tensors themselves: runs
        mutate tensor storage in place and mirror values into scope vars
        so ``global_scope().find_var(name).get_tensor()`` works. Any
        other scope holds its own copies: params are seeded from the
        current tensor values on first use (copy — the train step
        donates its input buffers), updates land in the scope, and the
        base tensor values are restored afterwards.
        """
        from ..core.tensor_array import global_scope, is_base_scope

        import weakref

        scope = scope if scope is not None else global_scope()
        state_targets = getattr(entry, "state_targets", [])
        tracked, seen, keys = [], set(), set()
        for t in list(params) + list(frozen) + list(state_targets):
            if id(t) in seen:
                continue
            seen.add(id(t))
            key = getattr(t, "name", None)
            if not isinstance(key, str) or not key or key in keys:
                key = "_anon_%d" % id(t)
            keys.add(key)
            tracked.append((key, t))
        if is_base_scope(scope):
            outs = entry(program, feed_vals, params, frozen)
            for key, t in tracked:
                # bind, don't copy: the base scope is a live view over
                # tensor storage — no dead program's arrays are pinned
                scope.var(key).bind(t)
            return outs
        # per-program executor state (opt slots, grad-merge acc, step)
        # resolves through the ancestor chain like the vars themselves:
        # a child-scope run over params owned by the parent must reuse
        # the parent's optimizer state, not re-initialize fresh moments
        est_scope = scope
        while est_scope is not None and program not in est_scope._exec_state:
            est_scope = est_scope._parent
        est = (scope if est_scope is None or is_base_scope(est_scope)
               else est_scope)._exec_state.setdefault(program, {})
        ts = program._train_spec
        opt = ts[1] if ts is not None else None
        saved = [(t, t._value) for _, t in tracked]
        saved_opt = (program._opt_state, getattr(program, "_gm_acc", None))
        saved_step = opt._global_step if opt is not None else None
        swapped = False
        holders = []
        try:
            for key, t in tracked:
                v, owner = scope._find_var_with_owner(key)
                stale_anon = (
                    key.startswith("_anon_") and v is not None
                    and (getattr(v, "_anon_for", None) is None
                         or v._anon_for() is not t))
                if (v is None or not v.is_initialized()
                        or is_base_scope(owner) or stale_anon):
                    # seed a local copy. Copy for two reasons: the
                    # compiled train step donates param buffers (the
                    # base tensor must survive the run), and a base-
                    # scope var resolved through the ancestor chain is
                    # only a live mirror of tensor storage — never real
                    # per-scope state to update in place. Anonymous keys
                    # are id-derived, so a var whose original tensor is
                    # gone (id recycled) is stale and must be reseeded.
                    v = scope.var(key).set(jnp.copy(t._value))
                    if key.startswith("_anon_"):
                        v._anon_for = weakref.ref(t)
                holders.append(v)
                t._value = v.get_tensor()
            program._opt_state = est.get("opt_state")
            program._gm_acc = est.get("gm_acc")
            if opt is not None:
                # per-scope step counter: a fresh scope's Adam bias
                # correction must start from step 1, matching its fresh
                # moment slots. (LR scheduler state remains user-stepped
                # and shared, as in eager mode.)
                opt._global_step = est.get("global_step", 0)
            swapped = True
            outs = entry(program, feed_vals, params, frozen)
            for (key, t), v in zip(tracked, holders):
                v.set(t._value)
                if key.startswith("_anon_"):
                    v._anon_for = weakref.ref(t)
        finally:
            if swapped:
                est["opt_state"] = program._opt_state
                est["gm_acc"] = getattr(program, "_gm_acc", None)
                program._opt_state, program._gm_acc = saved_opt
                if opt is not None:
                    est["global_step"] = opt._global_step
                    opt._global_step = saved_step
            for t, val in saved:
                t._value = val
        return outs

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """PS-style dataset training loop (reference
        Executor::RunFromDataset, executor.cc:163: TrainerFactory +
        worker threads over a DataFeed'd Dataset). The trainer class
        comes from program._fleet_opt (reference trainer_desc from the
        fleet optimizer): {"trainer": "DistMultiTrainer", "ps_runtime":
        ..., "sparse_tables": {...}, "push_grads_fn": ...} selects the
        Downpour pull/push workers."""
        from ..framework.trainer import TrainerFactory

        prog = _as_program(program)
        fleet_opt = getattr(prog, "_fleet_opt", None) or {}
        name = fleet_opt.get("trainer", "MultiTrainer")
        trainer = TrainerFactory().create_trainer(
            name, num_workers=thread or getattr(dataset, "_thread_num", 2))
        trainer.initialize(program=prog, executor=self,
                           fetch_list=fetch_list)
        if name == "DistMultiTrainer" and "ps_runtime" in fleet_opt:
            trainer.set_ps(fleet_opt["ps_runtime"],
                           fleet_opt.get("sparse_tables", {}),
                           fleet_opt.get("push_grads_fn"))
        trainer.run(dataset.batches())
        return trainer

    def infer_from_dataset(self, program=None, dataset=None, **kwargs):
        infer = _as_program(program).clone(for_test=True)
        return self.train_from_dataset(infer, dataset, **kwargs)

    # -----------------------------------------------------------------
    def _compile(self, program, feed_tensors, fetch_tensors, params, frozen):
        from ..framework import random as _random

        train = program._train_spec is not None
        grad_ids = list(program._grad_map.keys())
        # state edges (BatchNorm running stats etc.): replayed source
        # values are threaded out of the jitted module and written back
        state_list = list(program._state_updates.values())
        state_targets = [t for t, _ in state_list]
        state_sources = [s for _, s in state_list]

        if not train:
            program._replay_keep_ids = (
                {id(t) for t in fetch_tensors}
                | {id(s) for s in state_sources})

            def pure(feed_vals, param_vals, frozen_vals, rng_key):
                _random.set_replay_base(rng_key)
                try:
                    with _ReplayContext(program,
                                        params + frozen + state_targets):
                        for t, v in zip(feed_tensors, feed_vals):
                            t._value = v
                        for t, v in zip(params, param_vals):
                            t._value = v
                        for t, v in zip(frozen, frozen_vals):
                            t._value = v
                        _run_tape(program)
                        return ([t._value for t in fetch_tensors],
                                [s._value for s in state_sources])
                finally:
                    _random.set_replay_base(None)

            jitted = jax.jit(pure)

            def runner(prog, feed_vals, params, frozen):
                outs, new_state = jitted(
                    feed_vals, [p._value for p in params],
                    [f._value for f in frozen], _random.next_key())
                for t, v in zip(state_targets, new_state):
                    t._value = v
                return outs

            runner.state_targets = state_targets
            return runner

        loss_t, opt = program._train_spec
        has_update = opt is not None
        gm_k, gm_avg = getattr(program, "_grad_merge", (1, True))
        # ZeRO stages from the auto_parallel_sharding pass: stage>=1
        # shards optimizer state over 'sharding', stage>=2 constrains
        # grads to the same spec (XLA emits reduce-scatter), stage>=3
        # shards params (specs stamped by the pass itself)
        zero_stage = getattr(program, "_zero_stage", 0)
        zero_shardings = None
        if zero_stage >= 1:
            from jax.sharding import NamedSharding, PartitionSpec as _P

            from ..distributed import mesh as _zmesh
            from ..parallel.engine import zero_spec as _zero_spec

            zmesh = _zmesh.get_mesh()
            if "sharding" in zmesh.axis_names:
                zero_shardings = {
                    id(p): NamedSharding(
                        zmesh, _zero_spec(tuple(p.shape), _P(), zmesh))
                    for p in params}
        # tensors the segmented-recompute replay must expose as segment
        # outputs even when no later record consumes them
        program._replay_keep_ids = (
            {id(loss_t)} | {id(t) for t in fetch_tensors}
            | {id(s) for s in state_sources})

        def pure(feed_vals, param_vals, frozen_vals, opt_state, acc_grads,
                 lr, step, rng_key):
            _random.set_replay_base(rng_key)
            try:
                def loss_of(pvals):
                    with _ReplayContext(program,
                                        params + frozen + state_targets):
                        for t, v in zip(feed_tensors, feed_vals):
                            t._value = v
                        for t, v in zip(params, pvals):
                            t._value = v
                        for t, v in zip(frozen, frozen_vals):
                            t._value = v
                        _run_tape(program)
                        loss_val = loss_t._value
                        aux = ([t._value for t in fetch_tensors],
                               [s._value for s in state_sources])
                    return jnp.sum(loss_val), aux

                (loss_v, (fetches, state_vals)), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(param_vals)
            finally:
                _random.set_replay_base(None)
            if zero_stage >= 2 and zero_shardings is not None:
                grads = [
                    jax.lax.with_sharding_constraint(
                        g, zero_shardings[id(p)])
                    if id(p) in zero_shardings else g
                    for g, p in zip(grads, params)]
            # grad placeholders fetched by id
            grad_of = {pid: g for pid, g in zip(
                [id(p) for p in params], grads)}
            out_fetches = []
            for t, fv in zip(fetch_tensors, fetches):
                hit = None
                for pid, gt in program._grad_map.items():
                    if gt is t:
                        hit = grad_of.get(pid)
                        break
                out_fetches.append(fv if hit is None else hit)
            if not has_update:
                return (out_fetches, param_vals, opt_state, acc_grads,
                        state_vals)
            names = [str(i) for i in range(len(params))]
            # per-parameter hooks (decay exclusions) resolve through the
            # synthetic functional names to the real Parameters
            opt.set_functional_params(dict(zip(names, params)))
            if gm_k > 1:
                # gradient merge (auto_parallel_gradient_merge pass):
                # accumulate k microsteps, update on the k-th, where()
                # keeps params/state frozen in between
                acc_new = [a + g for a, g in zip(acc_grads, grads)]
                eff = [(a / gm_k if gm_avg else a) for a in acc_new]
                do_upd = (step % gm_k) == 0
                upd_step = jnp.maximum(step // gm_k, 1)
                pdict = dict(zip(names, param_vals))
                gdict = dict(zip(names, eff))
                sdict = dict(zip(names, opt_state))
                new_p, new_s = opt.functional_apply(pdict, gdict, sdict,
                                                    lr=lr, step=upd_step)
                out_p = [jnp.where(do_upd, new_p[n], p)
                         for n, p in zip(names, param_vals)]
                out_s = [
                    [jnp.where(do_upd, ns, os)
                     for ns, os in zip(new_s[n], slots)]
                    for n, slots in zip(names, opt_state)]
                acc_out = [jnp.where(do_upd, jnp.zeros_like(a), a)
                           for a in acc_new]
                return out_fetches, out_p, out_s, acc_out, state_vals
            pdict = dict(zip(names, param_vals))
            gdict = dict(zip(names, grads))
            sdict = dict(zip(names, opt_state))
            new_p, new_s = opt.functional_apply(pdict, gdict, sdict,
                                                lr=lr, step=step)
            return (out_fetches, [new_p[n] for n in names],
                    [new_s[n] for n in names], acc_grads, state_vals)

        jitted = jax.jit(pure, donate_argnums=(1, 3, 4))

        def runner(prog, feed_vals, params, frozen):
            if prog._opt_state is None:
                if has_update:
                    prog._opt_state = [
                        [opt._init_slot(s, p) for s in opt._slots()]
                        for p in params]
                    if zero_shardings is not None:
                        # ZeRO stage 1+: moment slots live sharded
                        prog._opt_state = [
                            [jax.device_put(s, zero_shardings[id(p)])
                             if jnp.shape(s) == tuple(p.shape) else s
                             for s in slots]
                            for slots, p in zip(prog._opt_state, params)]
                else:
                    prog._opt_state = [[] for _ in params]
            acc = getattr(prog, "_gm_acc", None)
            if acc is None:
                acc = ([jnp.zeros(p.shape, jnp.float32) for p in params]
                       if gm_k > 1 else [])
            lr = jnp.asarray(opt.get_lr() if has_update else 0.0,
                             jnp.float32)
            # eager Optimizer.step increments the global step before the
            # update (Adam bias correction needs step >= 1)
            step = jnp.asarray(
                opt._global_step + 1 if has_update else 1, jnp.int32)
            outs, new_p, new_s, new_acc, new_state = jitted(
                feed_vals, [p._value for p in params],
                [f._value for f in frozen], prog._opt_state, acc, lr,
                step, _random.next_key())
            for p, v in zip(params, new_p):
                p._value = v
            for t, v in zip(state_targets, new_state):
                t._value = v
            prog._opt_state = new_s
            prog._gm_acc = new_acc
            if has_update:
                opt._global_step += 1  # LR schedulers are stepped by user
            return outs

        runner.state_targets = state_targets
        return runner


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class BuildStrategy:
    def __init__(self):
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.enable_auto_fusion = True  # XLA always fuses


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..core.autograd import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)


# ---------------------------------------------------------------------------
# Inference model serialization: StableHLO via jax.export — the portable
# program format (the analog of the reference's saved ProgramDesc+params,
# static/io.py save_inference_model).
# ---------------------------------------------------------------------------

def _export_program(program, feed_tensors, fetch_tensors):
    from ..jit import export_with_dynamic_dims

    params, frozen = program._analyze()
    const_p = [p._value for p in params]
    const_f = [f._value for f in frozen]

    def pure(*feed_vals):
        with _ReplayContext(program, params + frozen):
            for t, v in zip(feed_tensors, feed_vals):
                t._value = v
            for t, v in zip(params, const_p):
                t._value = v
            for t, v in zip(frozen, const_f):
                t._value = v
            _run_tape(program)
            return [t._value for t in fetch_tensors]

    specs = [(getattr(v, "spec_shape", list(v.shape)), v._value.dtype)
             for v in feed_tensors]
    return export_with_dynamic_dims(pure, specs)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """Freeze params into a serialized StableHLO module + meta."""
    import os
    import pickle

    program = program or default_main_program()
    feed_vars = list(feed_vars)
    fetch_vars = [_fetch_tensor(program, f) for f in fetch_vars]
    blob = _export_program(program, feed_vars, fetch_vars)
    meta = {
        "feed": [v.name for v in feed_vars],
        "fetch": [getattr(v, "name", "fetch%d" % i)
                  for i, v in enumerate(fetch_vars)],
        "format": "stablehlo.jax_export.v1",
    }
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(blob)
    with open(path_prefix + ".pdmeta", "wb") as f:
        pickle.dump(meta, f, protocol=4)


class InferenceProgram:
    """Loaded frozen program: a deserialized StableHLO executable."""

    def __init__(self, exported, meta):
        self._exported = exported
        self.meta = meta
        self._call = jax.jit(exported.call)

    @property
    def feed_names(self):
        return list(self.meta["feed"])

    @property
    def fetch_names(self):
        return list(self.meta["fetch"])

    def run(self, *feed_vals):
        return self._call(*[jnp.asarray(np.asarray(v)) for v in feed_vals])


def load_inference_model(path_prefix, executor=None):
    import pickle

    from jax import export as jex

    from .ref_import import is_reference_format

    if is_reference_format(path_prefix):
        # a model saved by the REFERENCE framework (ProgramDesc protobuf
        # + combined params): import it (ref_import.py) so migrating
        # users can serve existing artifacts without re-export
        from .ref_import import load_reference_inference_model

        model = load_reference_inference_model(path_prefix)
        return model, model.feed_names, model.fetch_names
    with open(path_prefix + ".pdmodel", "rb") as f:
        blob = f.read()
    with open(path_prefix + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    prog = InferenceProgram(jex.deserialize(blob), meta)
    return prog, prog.feed_names, prog.fetch_names


# ---------------------------------------------------------------------------
# paddle.static.nn — graph builders + control flow over the shared eager ops.
# Control flow lowers to lax.cond / lax.while_loop (reference: block-attr
# ops in controlflow/, framework.proto attr type BLOCK).
# ---------------------------------------------------------------------------
from ..core.dispatch import primitive as _primitive  # noqa: E402
from ..core.dispatch import no_grad as _no_grad  # noqa: E402


def _wrap_all(vals):
    return [Tensor(v) for v in vals]


def _unwrap_all(out):
    if isinstance(out, Tensor):
        return [out._value], True
    seq = list(out) if isinstance(out, (tuple, list)) else [out]
    return [o._value if isinstance(o, Tensor) else jnp.asarray(o)
            for o in seq], False


@_primitive(name="while_loop", nondiff=True)
def _while_raw(loop_vars, cond=None, body=None):
    def c(vs):
        with _no_grad():
            r = cond(*_wrap_all(vs))
        return r._value.reshape(()) if isinstance(r, Tensor) else r

    def b(vs):
        with _no_grad():
            out = body(*_wrap_all(vs))
        flat, _ = _unwrap_all(out)
        return tuple(flat)

    return tuple(jax.lax.while_loop(c, b, tuple(loop_vars)))


@_primitive(name="cond")
def _cond_raw(operands, pred=None, true_fn=None, false_fn=None):
    def t(ops):
        with _no_grad():
            out = true_fn(*_wrap_all(ops)) if ops else true_fn()
        flat, _ = _unwrap_all(out)
        return tuple(flat)

    def f(ops):
        with _no_grad():
            out = false_fn(*_wrap_all(ops)) if ops else false_fn()
        flat, _ = _unwrap_all(out)
        return tuple(flat)

    p = pred.reshape(()) if hasattr(pred, "reshape") else pred
    return jax.lax.cond(p, t, f, tuple(operands))


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """reference paddle.static.nn.while_loop (controlflow/while_op).
    loop_vars must be explicit (same contract as the reference)."""
    out = _while_raw(list(loop_vars), cond=cond, body=body)
    return list(out) if isinstance(out, tuple) else [out]


def cond(pred, true_fn=None, false_fn=None, operands=None, name=None):
    """reference paddle.static.nn.cond (controlflow/conditional_block_op).
    Branches that close over tensors should take them via `operands`."""
    if isinstance(pred, Tensor):
        # under static recording the pred may depend on feeds at replay
        # time, so record the lax.cond op with the live Tensor; eagerly, a
        # concrete pred picks the branch in Python.
        if _recording_program() is None and not _is_traced(pred._value):
            pred = bool(np.asarray(pred._value))
    if isinstance(pred, bool):
        out = true_fn(*(operands or [])) if pred else \
            false_fn(*(operands or []))
        return out
    out = _cond_raw(list(operands or []), pred=pred,
                    true_fn=true_fn, false_fn=false_fn)
    if isinstance(out, tuple) and len(out) == 1:
        return out[0]
    return out


def _is_traced(v):
    return isinstance(v, jax.core.Tracer)


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = int(np.asarray(branch_index._value)) if isinstance(
        branch_index, Tensor) and not _is_traced(branch_index._value) \
        else branch_index
    fns = dict(branch_fns) if isinstance(branch_fns, (list, tuple)) and \
        branch_fns and isinstance(branch_fns[0], (list, tuple)) else branch_fns
    if isinstance(fns, dict) and isinstance(idx, int):
        fn = fns.get(idx, default)
        if fn is None:
            # reference semantics: no default → the max-key branch
            fn = fns[max(fns.keys())]
        return fn()
    raise NotImplementedError("traced switch_case requires int branch index")


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        p = bool(np.asarray(pred._value)) if isinstance(pred, Tensor) \
            else bool(pred)
        if p:
            return fn()
    if default is not None:
        return default()
    raise ValueError("no branch taken and no default in static.nn.case")


class nn:
    """paddle.static.nn subset: functional builders over the shared ops."""

    cond = staticmethod(cond)
    while_loop = staticmethod(while_loop)
    switch_case = staticmethod(switch_case)
    case = staticmethod(case)

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        import paddle_tpu as P

        flat = P.flatten(x, start_axis=num_flatten_dims) \
            if len(x.shape) > num_flatten_dims + 1 else x
        in_dim = 1
        for s in x.shape[num_flatten_dims:]:
            in_dim *= s
        w = P.create_parameter([in_dim, size])
        b = P.create_parameter([size])
        out = P.add(P.matmul(flat, w), b)
        if activation:
            out = getattr(P.nn.functional, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, param_attr=None, dtype="float32", name=None):
        import paddle_tpu as P

        w = P.create_parameter(list(size), dtype=dtype)
        return P.nn.functional.embedding(input, w)

    @staticmethod
    def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, **kwargs):
        import paddle_tpu as P

        bn = P.nn.BatchNorm2D(input.shape[1], momentum=momentum,
                              epsilon=epsilon)
        out = bn(input)
        if act:
            out = getattr(P.nn.functional, act)(out)
        return out

# static.nn builder completions (nn_extras.py)
from . import nn_extras as _nn_extras  # noqa: E402

for _name in _nn_extras.__all__:
    # class-attribute access on plain functions returns them unbound —
    # the same shape as the hand-written builders above
    setattr(nn, _name, getattr(_nn_extras, _name))
del _name, _nn_extras

# -- surface completions (places/guards/EMA/persistence/debug; extras.py) ----
from ..core.tensor_array import global_scope, scope_guard  # noqa: E402,F401
from .extras import (  # noqa: E402,F401
    ExponentialMovingAverage,
    IpuCompiledProgram,
    IpuStrategy,
    ParallelExecutor,
    Print,
    WeightNormParamAttr,
    accuracy,
    auc,
    cpu_places,
    create_global_var,
    create_parameter,
    ctr_metric_bundle,
    cuda_places,
    deserialize_persistables,
    deserialize_program,
    device_guard,
    exponential_decay,
    ipu_shard_guard,
    load,
    load_from_file,
    load_program_state,
    mlu_places,
    name_scope,
    normalize_program,
    npu_places,
    py_func,
    save,
    save_to_file,
    serialize_persistables,
    serialize_program,
    set_ipu_shard,
    set_program_state,
    xpu_places,
)
