"""paddle.static — whole-graph capture & execution.

The reference's static graph is ProgramDesc + Executor/InterpreterCore
(framework.proto:242, new_executor/). TPU-native: a Program is a traced jax
function (captured via the same eager ops running under jax.jit tracing);
the Executor compiles it to ONE XLA module per feed signature — what the
reference's paddle2cinn bridge aspired to. The guard-style API
(program_guard, data, Executor.run(feed, fetch_list)) is preserved.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from ..core import dtype as _dtype
from ..core.dispatch import no_grad
from ..core.tensor import Tensor
from ..jit import InputSpec  # noqa: F401

_state = threading.local()


def _enabled():
    return getattr(_state, "static_mode", False)


def enable_static():
    _state.static_mode = True


def disable_static():
    _state.static_mode = False


def in_dynamic_mode():
    return not _enabled()


class Variable(Tensor):
    """Placeholder variable in a Program (reference VarDesc). Holds spec
    only; values are bound at Executor.run via feed."""

    def __init__(self, name, shape, dtype):
        super().__init__(jnp.zeros([1 if s in (-1, None) else s
                                    for s in shape],
                                   _dtype.to_jax(dtype)))
        self.name = name
        self.spec_shape = list(shape)
        self.is_data = True


class Program:
    """Captured computation (reference ProgramDesc). Records feed vars,
    fetch construction function, and the python builder executed under
    program_guard."""

    def __init__(self):
        self.feed_vars = {}
        self.ops = []  # (fn, args, kwargs, out) trace, for introspection
        self._builders = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def var(self, name):
        return self.feed_vars.get(name)

    def list_vars(self):
        return list(self.feed_vars.values())


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return getattr(_state, "main_program", _default_main)


def default_startup_program():
    return getattr(_state, "startup_program", _default_startup)


class program_guard:
    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        self._prev = (getattr(_state, "main_program", None),
                      getattr(_state, "startup_program", None))
        _state.main_program = self.main
        _state.startup_program = self.startup or _default_startup
        return self

    def __exit__(self, *a):
        _state.main_program, _state.startup_program = self._prev
        return False


def data(name, shape, dtype="float32", lod_level=0):
    v = Variable(name, shape, dtype)
    default_main_program().feed_vars[name] = v
    return v


class Executor:
    """reference python/paddle/fluid/executor.py:921. run() re-executes the
    program builder with fed values, jit-compiling per feed signature."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        # bind feeds into the program's feed vars
        for name, value in feed.items():
            var = program.feed_vars.get(name)
            if var is not None:
                import numpy as np

                arr = np.asarray(value)
                var._value = jnp.asarray(arr)
        outs = []
        for f in fetch_list:
            t = f if isinstance(f, Tensor) else program.var(str(f))
            if isinstance(t, _DeferredFetch):
                t = t.evaluate()
            outs.append(t.numpy() if return_numpy else t)
        return outs


class _DeferredFetch:
    def __init__(self, fn):
        self.fn = fn

    def evaluate(self):
        return self.fn()


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class BuildStrategy:
    def __init__(self):
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.enable_auto_fusion = True  # XLA always fuses


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None):
    from ..jit import save as jit_save

    class _Holder:
        pass

    # persist fetch tensors' current params via the program's structure
    from ..framework.io import save as fsave

    fsave({"feed": [v.name for v in feed_vars],
           "fetch": [getattr(v, "name", str(i))
                     for i, v in enumerate(fetch_vars)]},
          path_prefix + ".pdmodel.meta")


def load_inference_model(path_prefix, executor):
    raise NotImplementedError(
        "static inference model loading lands with the predictor "
        "(paddle_tpu.inference)")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..core.autograd import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients,
                 allow_unused=True)


class nn:
    """paddle.static.nn subset: functional builders over the shared ops."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        import paddle_tpu as P

        flat = P.reshape(x, [x.shape[0], -1]) if num_flatten_dims == 1 else x
        w = P.create_parameter([flat.shape[-1], size])
        out = P.matmul(flat, w)
        if activation:
            out = getattr(P.nn.functional, activation)(out)
        return out
