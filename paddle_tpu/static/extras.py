"""static API completions: persistence, places, guards, EMA, debug ops.

Parity: reference python/paddle/static/__init__.py surface beyond the
core Program/Executor (implemented in static/__init__.py). Program
serialization rides the same jax.export StableHLO path as
save_inference_model; parameter state rides framework.io pickles.
"""
from __future__ import annotations

import contextlib
import pickle

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "cpu_places", "cuda_places", "xpu_places", "npu_places", "mlu_places",
    "device_guard", "name_scope", "create_global_var", "create_parameter",
    "ExponentialMovingAverage", "WeightNormParamAttr", "Print", "py_func",
    "accuracy", "auc", "ctr_metric_bundle", "exponential_decay",
    "save", "load", "save_to_file", "load_from_file",
    "serialize_program", "deserialize_program",
    "serialize_persistables", "deserialize_persistables",
    "load_program_state", "set_program_state", "normalize_program",
    "ParallelExecutor", "IpuCompiledProgram", "IpuStrategy",
    "ipu_shard_guard", "set_ipu_shard",
]


# -- places ------------------------------------------------------------------

def cpu_places(device_count=None):
    """reference static.cpu_places: list of CPUPlace."""
    from ..core.place import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """On the TPU stack the accelerator places are TPU devices."""
    from ..core.place import TPUPlace
    import jax

    ids = device_ids if device_ids is not None \
        else range(len(jax.devices()))
    return [TPUPlace(i) for i in ids]


def xpu_places(device_ids=None):
    raise RuntimeError("no XPU backend on the TPU stack; use cuda_places "
                       "(mapped to TPU devices) or cpu_places")


def npu_places(device_ids=None):
    raise RuntimeError("no NPU backend on the TPU stack")


def mlu_places(device_ids=None):
    raise RuntimeError("no MLU backend on the TPU stack")


# -- guards ------------------------------------------------------------------

@contextlib.contextmanager
def device_guard(device=None):
    """reference static.device_guard: pins ops to a device in the
    ProgramDesc. Under XLA the partitioner owns placement, so the guard
    records intent only (documented deviation)."""
    yield


@contextlib.contextmanager
def name_scope(prefix=None):
    """reference static.name_scope: prefixes op names for debugging;
    names here come from the op registry, so the scope is advisory."""
    from ..utils import unique_name

    with unique_name.guard((prefix or "scope") + "/"):
        yield


# -- vars --------------------------------------------------------------------

def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference static.create_global_var: a filled persistent Tensor."""
    t = Tensor(jnp.full(shape, value, dtype))
    t.name = name or "global_var"
    t.persistable = persistable
    t.stop_gradient = True
    return t


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference static.create_parameter (delegates to the top-level
    parameter factory the eager builders already use)."""
    import paddle_tpu as P

    return P.create_parameter(shape, dtype=dtype,
                              default_initializer=default_initializer)


class WeightNormParamAttr:
    """reference static.WeightNormParamAttr: ParamAttr marker requesting
    weight normalization; consumed by nn.utils.weight_norm here."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable


# -- EMA ---------------------------------------------------------------------

class ExponentialMovingAverage:
    """reference static.ExponentialMovingAverage: shadow = decay*shadow +
    (1-decay)*param, with apply()/restore() swap semantics."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._params = []

    def _register(self, parameters):
        for p in parameters:
            key = id(p)
            if key not in self._shadow:
                self._params.append(p)
                self._shadow[key] = jnp.asarray(p._value)

    def update(self, parameters=None):
        """One EMA step over the given (or previously seen) params."""
        if parameters is not None:
            self._register(parameters)
        d = self._decay
        for p in self._params:
            key = id(p)
            self._shadow[key] = d * self._shadow[key] \
                + (1.0 - d) * jnp.asarray(p._value)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap shadow weights in (evaluation), restoring on exit."""
        for p in self._params:
            self._backup[id(p)] = p._value
            p._value = self._shadow[id(p)]
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._value = self._backup.pop(id(p))


# -- debug / misc ops --------------------------------------------------------

def Print(input, first_n=-1, message=None, summarize=20,  # noqa: N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference static.Print: log a tensor's value as a side effect and
    pass it through. Inside jit this lowers to jax.debug.print."""
    import jax

    v = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    jax.debug.print("{m} shape={s} value={v}",
                    m=message or "", s=v.shape, v=v)
    return input


def py_func(func, x, out=None, backward_func=None, skip_vars_in_backward_input=None):
    """reference static.py_func: run a python callable on tensors. The
    eager/tape engines call python directly, so this is a checked
    passthrough (backward_func unsupported: use PyLayer for custom vjp)."""
    if backward_func is not None:
        raise NotImplementedError(
            "py_func backward_func: define a paddle_tpu.autograd.PyLayer "
            "instead (custom vjp)")
    xs = x if isinstance(x, (list, tuple)) else [x]
    return func(*xs)


def accuracy(input, label, k=1, correct=None, total=None):
    """reference static.accuracy (metric op form)."""
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """reference static.auc: returns (auc_value, batch_stats...) — here
    the scalar AUC over this batch via the streaming Auc metric."""
    from ..metric import Auc

    m = Auc(curve=curve, num_thresholds=num_thresholds)
    m.update(np.asarray(input._value if isinstance(input, Tensor)
                        else input),
             np.asarray(label._value if isinstance(label, Tensor)
                        else label))
    return Tensor(jnp.asarray(m.accumulate()))


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """reference static.ctr_metric_bundle: (auc, abserr, sqrerr, prob,
    q, pos, total) aggregate tensors for CTR training."""
    pv = np.asarray(input._value if isinstance(input, Tensor) else input) \
        .reshape(-1)
    lv = np.asarray(label._value if isinstance(label, Tensor) else label) \
        .reshape(-1).astype(np.float64)
    abserr = np.abs(pv - lv).sum()
    sqrerr = ((pv - lv) ** 2).sum()
    prob = pv.sum()
    pos = lv.sum()
    total = float(lv.size)
    auc_v = auc(Tensor(jnp.asarray(pv[:, None])),
                Tensor(jnp.asarray(lv[:, None])))
    return (auc_v, Tensor(jnp.asarray(abserr)), Tensor(jnp.asarray(sqrerr)),
            Tensor(jnp.asarray(prob)), Tensor(jnp.asarray(prob / total)),
            Tensor(jnp.asarray(pos)), Tensor(jnp.asarray(total)))


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """fluid-era schedule fn → the modern scheduler object (reference
    maps it the same way in 2.x)."""
    from ..optimizer.lr import ExponentialDecay

    return ExponentialDecay(learning_rate=learning_rate, gamma=decay_rate)


# -- program persistence -----------------------------------------------------

def _param_table(program):
    """name -> Parameter for a tape Program (its captured trainable
    leaves, Program._analyze)."""
    from . import default_main_program

    prog = program if program is not None else default_main_program()
    if hasattr(prog, "state_dict"):
        return prog.state_dict()
    params, _ = prog._analyze()
    return {getattr(p, "name", None) or "param_%d" % i: p
            for i, p in enumerate(params)}


def _state_of(program):
    return {name: np.asarray(t._value)
            for name, t in _param_table(program).items()}


def save(program, model_prefix, protocol=4):
    """reference static.save: <prefix>.pdparams + <prefix>.pdmodel."""
    state = program.state_dict() if hasattr(program, "state_dict") \
        else _state_of(program)
    with open(model_prefix + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    with open(model_prefix + ".pdmodel", "wb") as f:
        pickle.dump({"format": "paddle_tpu.program.state.v1"}, f,
                    protocol=protocol)


def load(program, model_prefix, executor=None, var_list=None):
    """reference static.load: restore params saved by static.save."""
    with open(model_prefix + ".pdparams", "rb") as f:
        state = pickle.load(f)
    set_program_state(program, state)
    return state


def load_program_state(model_prefix, var_list=None):
    """reference static.load_program_state."""
    with open(model_prefix + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    """reference static.set_program_state: push a name->ndarray dict into
    the program's parameters (matched by name over the captured
    trainable leaves)."""
    if hasattr(program, "set_state_dict"):
        program.set_state_dict(state_dict)
        return
    params = _param_table(program)
    missing = [n for n in state_dict if n not in params]
    for name, val in state_dict.items():
        if name in params:
            params[name]._value = jnp.asarray(val)
    if missing:
        raise ValueError(
            "set_program_state: %d entries matched no program parameter "
            "(e.g. %s)" % (len(missing), missing[:3]))


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """reference static.serialize_program -> bytes (StableHLO module,
    the same artifact save_inference_model writes)."""
    from . import _export_program, default_main_program

    prog = program if program is not None else default_main_program()
    fetches = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    return _export_program(prog, list(feeds), list(fetches))


def deserialize_program(data):
    """reference static.deserialize_program: bytes -> runnable program."""
    from jax import export as jex

    from . import InferenceProgram

    return InferenceProgram(jex.deserialize(data),
                            {"feed": [], "fetch": [],
                             "format": "stablehlo.jax_export.v1"})


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    """reference static.serialize_persistables -> bytes."""
    prog = program
    state = prog.state_dict() if hasattr(prog, "state_dict") \
        else _state_of(prog)
    return pickle.dumps(state, protocol=4)


def deserialize_persistables(program, data, executor=None):
    """reference static.deserialize_persistables."""
    set_program_state(program, pickle.loads(data))


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """reference static.normalize_program: prune to the feed->fetch
    closure. The tape Program replays exactly what was recorded between
    feeds and fetches, so normalization is identity here (XLA DCEs the
    rest at compile)."""
    return program


# -- n/a shims (vendor/executor machinery XLA replaces) ----------------------

class ParallelExecutor:
    """reference ParallelExecutor (legacy multi-GPU SSA executor,
    SURVEY §2.2): superseded by SPMD sharding — construct refuses with
    the modern path named."""

    def __init__(self, *a, **k):
        raise RuntimeError(
            "ParallelExecutor is replaced by SPMD sharding: build a mesh "
            "(paddle_tpu.distributed.mesh.build_hybrid_mesh) and run the "
            "plain Executor / CompiledTrainStep")


class IpuStrategy:
    def __init__(self, *a, **k):
        raise RuntimeError("no IPU backend on the TPU stack")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise RuntimeError("no IPU backend on the TPU stack")


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    raise RuntimeError("no IPU backend on the TPU stack")
    yield


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise RuntimeError("no IPU backend on the TPU stack")
