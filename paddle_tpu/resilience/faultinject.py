"""Deterministic fault injection: seeded, schedule-driven chaos.

The observability stack (PRs 2/3/5/6) can *name* every production
failure — a diverging rank, a stalled bracket, a wedged request — but
the recovery paths that must *act* on one (store reconnect, elastic
membership rebuild, serving quarantine/shed, snapshot resume) are
exactly the code that never runs in a clean CI pass. This module makes
every failure path below reproducible: named **injection sites**
threaded into the store ops, the eager collectives, the serving engine
step, and the compiled train step fire faults on a deterministic,
seeded schedule, so a chaos test replays the same incident every run.

Sites (the contract between this module and the instrumented code):

    store.set / store.get / store.add / store.delete   TCPStore ops
    pg.<op>            StoreProcessGroup collectives (pg.all_reduce, …)
    serving.step       top of Engine.step (engine-level transient)
    serving.prefill    per-request prefill (poison-request path)
    serving.decode     batched decode dispatch (quarantine path)
    train.step         CompiledTrainStep.__call__
    train.run_steps    CompiledTrainStep.run_steps
    snapshot.save      ResilientTrainLoop snapshot write
    mem.oom            deterministic OOM stand-in on the engine hot
                       paths (armed only while FLAGS_monitor_memory
                       latched a tracker; monitor/memory.py treats the
                       InjectedFault exactly like RESOURCE_EXHAUSTED,
                       so the postmortem path is CPU-testable)

Fault kinds:

    error      raise InjectedFault at the site
    delay      sleep ``arg`` seconds (default 0.05), then proceed
    drop       site-cooperative: the op is silently skipped (a set
               that never lands, a get that times out) — returned to
               the caller as the string "drop"
    broken_fd  site-cooperative (store ops): the client fd is closed
               under the caller's lock before the op, exercising the
               reconnect path — returned as "broken_fd"
    lost_ack   site-cooperative (retrying store ops): the request is
               SENT and applied server-side, but the reply is
               discarded so the client's retry path resends the op —
               the exactly-once window the nonce-idempotent ``add``
               closes; returned as "lost_ack"

Schedule grammar (``PT_FAULT_SCHEDULE`` / ``enable(schedule)``),
semicolon-separated rules::

    site:kind[=arg][@when]

    when := N        fire on the Nth hit of the site (1-based), once
          | N..      every hit from the Nth on
          | N..M     hits N through M inclusive
          | pFLOAT   probability per hit (seeded — deterministic)
          | %N       every Nth hit
    (no @when = every hit)

    PT_FAULT_SCHEDULE="store.set:error@3;serving.prefill:error@p0.2"
    PT_FAULT_SCHEDULE="store.get:broken_fd@2;pg.all_reduce:delay=0.2@%4"

Discipline (the PR-2/5/6 contract, test-pinned): default OFF via
``FLAGS_fault_inject``; while off every ``fire()`` is one attribute
load + branch — no RNG, no locks, no threads, no native calls, no
allocations. Sites are also compiled out of artifacts: the disabled
path never constructs rule state. Stdlib-only so worker processes can
import it without an accelerator backend.
"""
from __future__ import annotations

import os
import random
import threading
import time

from ..monitor import registry as _registry
from ..monitor.timeseries import _flag

_FAULTS = _registry.counter(
    "faults_injected_total",
    "faults fired by the injection framework (resilience/faultinject)",
    labelnames=("site", "kind"))

_KINDS = ("error", "delay", "drop", "broken_fd", "lost_ack")


class InjectedFault(RuntimeError):
    """A deliberately injected failure (never a real bug). Recovery
    code may match on this type; production code must treat it exactly
    like the organic failure it models."""

    def __init__(self, site, rule):
        super().__init__(
            "injected fault at site %r (rule %s)" % (site, rule))
        self.site = site
        self.rule = rule


class Rule:
    """One schedule entry: fire ``kind`` at ``site`` when the site's
    hit index (1-based, counted per rule) matches ``when``."""

    __slots__ = ("site", "kind", "arg", "when", "hits", "fired",
                 "mismatched")

    def __init__(self, site, kind, arg=None, when=None):
        if kind not in _KINDS:
            raise ValueError(
                "unknown fault kind %r (one of %s)" % (kind, _KINDS))
        self.site = site
        self.kind = kind
        self.arg = arg
        self.when = when            # None | (lo, hi) | ("p", prob) | ("%", n)
        self.hits = 0
        self.fired = 0
        # rule matched a site that cannot apply its kind (e.g. "drop"
        # at a collective): counted here, NEVER into the fired/metric
        # totals — a schedule that injects nothing must not report
        # that it did
        self.mismatched = 0

    def _matches(self, rng):
        n = self.hits
        w = self.when
        if w is None:
            return True
        if w[0] == "p":
            return rng.random() < w[1]
        if w[0] == "%":
            return n % w[1] == 0
        lo, hi = w
        return lo <= n <= (hi if hi is not None else n)

    def __str__(self):
        arg = "=%s" % self.arg if self.arg is not None else ""
        if self.when is None:
            when = ""
        elif self.when[0] == "p":
            when = "@p%g" % self.when[1]
        elif self.when[0] == "%":
            when = "@%%%d" % self.when[1]
        else:
            lo, hi = self.when
            when = "@%d" % lo if hi == lo else (
                "@%d.." % lo if hi is None else "@%d..%d" % (lo, hi))
        return "%s:%s%s%s" % (self.site, self.kind, arg, when)


def parse_schedule(spec):
    """Schedule string -> [Rule]; raises ValueError on a bad rule (a
    silently-ignored typo'd schedule would be a chaos test that tests
    nothing)."""
    rules = []
    for part in str(spec).split(";"):
        part = part.strip()
        if not part:
            continue
        try:
            site, _, rest = part.partition(":")
            if not site or not rest:
                raise ValueError("need site:kind")
            when = None
            if "@" in rest:
                rest, _, w = rest.partition("@")
                if w.startswith("p"):
                    when = ("p", float(w[1:]))
                elif w.startswith("%"):
                    n = int(w[1:])
                    if n < 1:       # %0 would div-by-zero at FIRE time,
                        raise ValueError(  # deep inside a production op
                            "every-Nth trigger needs N >= 1")
                    when = ("%", n)
                elif ".." in w:
                    lo, _, hi = w.partition("..")
                    when = (int(lo), int(hi) if hi else None)
                else:
                    when = (int(w), int(w))
            arg = None
            if "=" in rest:
                rest, _, a = rest.partition("=")
                arg = float(a)
            rules.append(Rule(site, rest, arg, when))
        except ValueError as e:
            raise ValueError(
                "bad fault rule %r: %s (grammar: site:kind[=arg][@when])"
                % (part, e))
    return rules


class _State:
    __slots__ = ("enabled", "rules", "seed", "rng", "lock", "site_hits")

    def __init__(self):
        self.enabled = False
        self.rules = []
        self.seed = 0
        self.rng = None
        self.lock = threading.Lock()
        self.site_hits = {}


_state = _State()


def enable(schedule=None, seed=None):
    """Arm the framework (process-wide). ``schedule`` is a spec string
    or a list of Rules; defaults to ``PT_FAULT_SCHEDULE``. ``seed``
    fixes the probabilistic rules' RNG (default ``PT_FAULT_SEED`` or
    0) — same seed + same schedule + same call sequence = same faults."""
    if schedule is None:
        schedule = os.environ.get("PT_FAULT_SCHEDULE", "")
    rules = (list(schedule) if isinstance(schedule, (list, tuple))
             else parse_schedule(schedule))
    if seed is None:
        seed = int(os.environ.get("PT_FAULT_SEED", "0"))
    with _state.lock:
        _state.rules = rules
        _state.seed = int(seed)
        _state.rng = random.Random(int(seed))
        _state.site_hits = {}
        _state.enabled = True
    return rules


def disable():
    """Disarm: every ``fire()`` returns to the one-branch fast path.
    Rule hit/fired counts are kept for post-run inspection."""
    _state.enabled = False


def is_enabled():
    return _state.enabled


def fire(site, _supports=(), **ctx):
    """Injection site hook. Returns None (no fault, or a fault the
    framework handled itself: delay) or an action string the CALLER
    must apply ("drop", "broken_fd"). Raises InjectedFault for kind
    "error".

    ``_supports`` declares which cooperative kinds THIS site can
    apply; a rule whose kind the site cannot honor counts as
    ``mismatched`` (visible in ``state()``), never as injected — the
    metrics must not claim chaos that never happened.

    The disabled path is one attribute load + branch; hot call sites
    additionally guard with ``is_enabled()`` so they build no ctx
    dict/strings while off (the zero-allocation contract)."""
    if not _state.enabled:
        return None
    return _fire(site, _supports, ctx)


def _fire(site, supports, ctx):
    action = None
    with _state.lock:
        _state.site_hits[site] = _state.site_hits.get(site, 0) + 1
        for rule in _state.rules:
            if rule.site != site:
                continue
            rule.hits += 1
            if not rule._matches(_state.rng):
                continue
            if rule.kind in ("drop", "broken_fd", "lost_ack") \
                    and rule.kind not in supports:
                rule.mismatched += 1
                continue
            rule.fired += 1
            action = rule
            break
    if action is None:
        return None
    rule = action
    _FAULTS.labels(site=site, kind=rule.kind).inc()
    if rule.kind == "delay":
        time.sleep(rule.arg if rule.arg is not None else 0.05)
        return None
    if rule.kind == "error":
        raise InjectedFault(site, str(rule))
    return rule.kind         # "drop" | "broken_fd": caller cooperates


def state():
    """JSON-ready snapshot for /debugz/resilience: schedule, per-site
    hit counts, per-rule fired counts."""
    with _state.lock:
        return {
            "enabled": _state.enabled,
            "seed": _state.seed,
            "rules": [{"rule": str(r), "site": r.site, "kind": r.kind,
                       "hits": r.hits, "fired": r.fired,
                       "mismatched": r.mismatched}
                      for r in _state.rules],
            "site_hits": dict(_state.site_hits),
        }


# FLAGS_fault_inject bootstraps the framework at import like the other
# monitor flags: a worker process started with the flag + schedule env
# injects from its first store op.
if _flag("FLAGS_fault_inject"):
    enable()
