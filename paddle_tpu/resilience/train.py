"""ResilientTrainLoop: periodic async snapshots + detect→recover→resume.

The repo could *name* a dead rank (ElasticManager verdicts, watchdog
postmortems) but nothing consumed the verdict: a killed rank or a
broken store socket ended the job. This loop closes the cycle around a
``CompiledTrainStep``:

1. **Snapshot** — every ``snapshot_every`` steps the full training
   state (params, optimizer slots, step counter, RNG key+counter) is
   captured to host and written OFF the critical path by one background
   writer thread, in the ``distributed/checkpoint`` format
   (``index.json`` + ``.npy``), into ``snap_<step>`` dirs finalized by
   an atomic rename — a kill mid-write can never leave a half snapshot
   that resume would trust. Retention keeps the newest ``keep``.

2. **Detect** — after every step the loop consumes
   ``ElasticManager.watch()`` (the so-far-unconsumed RESTART/ERROR
   verdicts): membership shrank → ``elastic.last_dead`` names who. A
   step exception (an injected store fault, a collective timeout
   because a peer died) routes through the same funnel: if the elastic
   verdict confirms a death within ``2*ttl`` it is a ``rank_death``,
   otherwise a ``step_error``.

3. **Recover** — ``rank_death``: survivors settle one TTL, the lowest
   alive rank (leader) publishes the new member set + resume step under
   a generation-suffixed store key, everyone barriers on the
   generation-suffixed name (safe to reuse names across generations —
   the round-based store barrier), ``elastic.set_members`` shrinks the
   watch set, and the ``on_generation`` callback lets the caller
   rebuild rank-aware state (a StoreProcessGroup over the survivors).
   ``step_error``/``watchdog``: restore only.

4. **Resume** — reload the chosen snapshot (params + opt slots through
   the optimizer's functional-load bridge, step counter, RNG state) and
   continue from its step. With a deterministic ``batch_fn(step)`` the
   post-recovery loss trajectory is bit-identical to an uninterrupted
   run from that snapshot (test-pinned).

Caveats (documented, not silent): snapshots store the global logical
arrays without partition specs (reload re-shards via the step's jit
in_shardings — exact for replicated-param configs, which is every
config this loop targets); quantized-grad-sync error-feedback residuals
are not snapshotted (flag-off default; a resume under the flag restarts
EF from zero, within its documented approximation).

Watchdog escalation: ``enable_watchdog_escalation()`` registers this
loop as a stall action — under ``PT_WATCHDOG_ACTION=recover`` a stalled
bracket requests a snapshot restore instead of only writing a
postmortem (the hook only sets a flag; the loop acts at the next step
boundary, never from the daemon thread).
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time

import numpy as np

from ..monitor import registry as _mreg
from . import faultinject as _fi

RECOVERIES = _mreg.counter(
    "recoveries_total",
    "resilience recovery episodes completed, by trigger kind",
    labelnames=("kind",))
SNAPSHOTS = _mreg.counter(
    "snapshots_total", "training snapshots completed (atomic rename)")
SNAPSHOT_ERRORS = _mreg.counter(
    "snapshot_errors_total",
    "snapshot writes that FAILED (full disk, bad dir) — a flat "
    "snapshots_total with this climbing means recovery has nothing to "
    "resume from")
SNAPSHOT_SECONDS = _mreg.histogram(
    "snapshot_seconds",
    "wall seconds of the OFF-critical-path snapshot write (capture to "
    "host is separate and synchronous)",
    buckets=(.005, .01, .025, .05, .1, .25, .5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0))
SNAPSHOT_CAPTURE_SECONDS = _mreg.histogram(
    "snapshot_capture_seconds",
    "wall seconds the TRAIN LOOP pays per snapshot (device->host "
    "capture; the critical-path cost of resilience)",
    buckets=(.001, .0025, .005, .01, .025, .05, .1, .25, .5, 1.0, 2.5,
             5.0))

_SNAP_PREFIX = "snap_"
_TMP_PREFIX = ".tmp-snap_"


def _snap_name(step):
    return "%s%08d" % (_SNAP_PREFIX, step)


def list_snapshots(snapshot_dir):
    """Complete snapshots (finalized dirs with an index) -> sorted
    step list. Tmp dirs from a killed writer are invisible here."""
    steps = []
    try:
        names = os.listdir(snapshot_dir)
    except OSError:
        return []
    for n in names:
        if not n.startswith(_SNAP_PREFIX):
            continue
        if not os.path.exists(os.path.join(snapshot_dir, n,
                                           "index.json")):
            continue
        try:
            steps.append(int(n[len(_SNAP_PREFIX):]))
        except ValueError:
            continue
    return sorted(steps)


class _SnapshotWriter:
    """One background thread serializing snapshot writes: tmp dir →
    save_state_dict → atomic rename → retention prune. At most one
    pending write; a snapshot requested while one is in flight is
    skipped (the next cadence tick catches up) — the train loop never
    blocks on disk."""

    def __init__(self, snapshot_dir, keep):
        self.snapshot_dir = snapshot_dir
        self.keep = max(1, int(keep))
        self._busy = threading.Event()
        self._work = None
        self._cv = threading.Condition()
        self._stop = False
        self._thread = None
        self.skipped = 0
        self.errors = []

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="pt-snapshot-writer", daemon=True)
            self._thread.start()

    def submit(self, step, state, extras):
        with self._cv:
            if self._work is not None or self._busy.is_set():
                self.skipped += 1
                return False
            self._work = (step, state, extras)
            self._ensure_thread()
            self._cv.notify()
        return True

    def flush(self, timeout_s=60):
        """Wait for the in-flight/pending write to land."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                if self._work is None and not self._busy.is_set():
                    return True
            time.sleep(0.01)
        return False

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self):
        while True:
            with self._cv:
                while self._work is None and not self._stop:
                    self._cv.wait(0.25)
                if self._stop and self._work is None:
                    return
                step, state, extras = self._work
                self._work = None
                self._busy.set()
            try:
                self._write(step, state, extras)
            except Exception as e:
                # a silently-swallowed write failure would surface
                # hours later as "no complete snapshot to resume from"
                # — make it loud NOW, on both stderr and the registry
                self.errors.append((step, repr(e)))
                SNAPSHOT_ERRORS.inc()
                sys.stderr.write(
                    "paddle_tpu.resilience: snapshot write for step %d "
                    "FAILED under %r: %r\n"
                    % (step, self.snapshot_dir, e))
            finally:
                self._busy.clear()

    def _write(self, step, state, extras):
        from ..distributed import checkpoint as _ckpt

        t0 = time.perf_counter()
        tmp = os.path.join(self.snapshot_dir, _TMP_PREFIX + "%08d" % step)
        final = os.path.join(self.snapshot_dir, _snap_name(step))
        shutil.rmtree(tmp, ignore_errors=True)
        # mesh=None would consult the global mesh from this thread;
        # the index's mesh_axes field is informational only for these
        # replicated host arrays, so the capture thread's mesh rides in
        _ckpt.save_state_dict(state, tmp, mesh=extras.pop("__mesh__"),
                              extras=extras)
        if os.path.isdir(final):
            shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)
        SNAPSHOTS.inc()
        SNAPSHOT_SECONDS.observe(time.perf_counter() - t0)
        self._prune()

    def _prune(self):
        steps = list_snapshots(self.snapshot_dir)
        for s in steps[:-self.keep]:
            shutil.rmtree(os.path.join(self.snapshot_dir, _snap_name(s)),
                          ignore_errors=True)


class ResilientTrainLoop:
    """Detect→recover→resume driver around one CompiledTrainStep.

    train_step   the compiled step (owns model/optimizer/step counter)
    batch_fn     deterministic data source: batch_fn(step_index) ->
                 (*inputs, labels) for 1-based global step index —
                 resume replays exactly the batches the lost steps saw
    snapshot_dir snapshots land here as ``snap_<step>`` dirs
    elastic      optional ElasticManager: membership detect + rebuild
    snapshot_every  cadence in steps (0 = only explicit snapshot())
    keep         snapshot retention
    post_step    optional hook(step_index, loss_float) -> loss_float,
                 e.g. a cross-rank loss all-reduce; its exceptions run
                 the same recovery funnel as step exceptions
    on_generation  hook(generation, members, info) after a membership
                 rebuild — rebuild rank-aware state here
    max_recoveries  hard cap; exceeding it re-raises (no retry storm)
    """

    def __init__(self, train_step, batch_fn, snapshot_dir, elastic=None,
                 snapshot_every=0, keep=2, post_step=None,
                 on_generation=None, max_recoveries=8,
                 store_timeout_s=60, steps_per_call=1):
        self.steps_per_call = int(steps_per_call)
        self.train_step = train_step
        self.model = train_step.model
        self.optimizer = train_step.optimizer
        self.batch_fn = batch_fn
        self.snapshot_dir = snapshot_dir
        self.elastic = elastic
        self.snapshot_every = int(snapshot_every)
        self.post_step = post_step
        self.on_generation = on_generation
        self.max_recoveries = int(max_recoveries)
        self.store_timeout_s = float(store_timeout_s)
        self.generation = 0
        self._last_watch = 0.0
        self.recoveries = 0
        self.recovery_log = []      # [(kind, resumed_step)]
        self._recover_requested = None
        self._writer = _SnapshotWriter(snapshot_dir, keep)
        os.makedirs(snapshot_dir, exist_ok=True)

    # -- snapshots --------------------------------------------------------

    def _capture(self):
        """Device→host capture of the full resume state. Runs on the
        train loop thread (the only thread that may read live training
        state); the disk write happens on the writer thread."""
        import jax

        from ..distributed import checkpoint as _ckpt
        from ..framework import random as _random

        t0 = time.perf_counter()
        # the array-vs-extras split is checkpoint.py's ONE predicate;
        # here we additionally materialize arrays to host numpy so the
        # background writer never touches live device state
        state, extras = _ckpt.split_model_state(self.model,
                                                self.optimizer)
        state = {k: np.asarray(v._value if hasattr(v, "_value") else v)
                 for k, v in state.items()}
        extras["step"] = int(self.train_step._step_count)
        extras["__mesh__"] = self.train_step.mesh
        key, counter = _random.get_rng_state()
        state["__rng__.key_data"] = np.asarray(jax.random.key_data(key))
        extras["__rng__.counter"] = int(counter)
        SNAPSHOT_CAPTURE_SECONDS.observe(time.perf_counter() - t0)
        return state, extras

    def snapshot(self):
        """Capture now + hand the write to the background thread.
        Returns the snapshot step, or None when skipped (writer busy or
        an injected snapshot fault)."""
        try:
            _fi.fire("snapshot.save", step=self.train_step._step_count)
        except _fi.InjectedFault:
            return None         # a failed snapshot never fails training
        step = int(self.train_step._step_count)
        state, extras = self._capture()
        return step if self._writer.submit(step, state, extras) else None

    def flush_snapshots(self, timeout_s=60):
        return self._writer.flush(timeout_s)

    def latest_snapshot_step(self):
        steps = list_snapshots(self.snapshot_dir)
        return steps[-1] if steps else None

    def restore(self, step=None):
        """Reload snapshot ``step`` (default: latest complete): params,
        optimizer slots (through the functional-load bridge, which also
        restores the compiled step counter), and the RNG key+counter.
        Returns the restored step."""
        import jax
        import jax.numpy as jnp

        from ..distributed import checkpoint as _ckpt
        from ..framework import random as _random

        self.flush_snapshots()
        if step is None:
            step = self.latest_snapshot_step()
        if step is None:
            raise RuntimeError(
                "no complete snapshot under %r to resume from"
                % self.snapshot_dir)
        path = os.path.join(self.snapshot_dir, _snap_name(step))
        _ckpt.load_model(self.model, self.optimizer, path,
                         mesh=self.train_step.mesh)
        with open(os.path.join(path, "index.json")) as f:
            index = json.load(f)
        extras = index.get("extras", {})
        meta = index["arrays"].get("__rng__.key_data")
        if meta is not None:
            arr = np.load(os.path.join(path, meta["file"]))
            _random.set_rng_state((
                jax.random.wrap_key_data(jnp.asarray(arr)),
                int(extras.get("__rng__.counter", 0))))
        # set_state_dict drove the optimizer's functional-load hook;
        # pin the loop-visible counter to the snapshot regardless
        self.train_step._step_count = int(extras.get("step", step))
        return step

    # -- watchdog escalation ----------------------------------------------

    def enable_watchdog_escalation(self):
        """Register as a watchdog stall action: under
        ``PT_WATCHDOG_ACTION=recover`` a stall requests a restore at
        the next step boundary (the hook never mutates training state
        from the daemon thread)."""
        from ..monitor import watchdog as _wd

        def _action(stalls, report):
            self._recover_requested = "watchdog"

        self._wd_action = _action
        _wd.register_stall_action(_action)
        return _action

    # -- detect / recover -------------------------------------------------

    def _verdict_bad(self, throttled=False):
        """One membership check. ``throttled=True`` (the per-step call)
        rate-limits to one check per heartbeat interval: watch() costs
        a store round-trip per member, detection latency is bounded by
        the TTL (seconds) anyway, and ms-scale steps must not pay
        world_size blocking RPCs each."""
        from ..distributed.elastic import ElasticStatus

        if self.elastic is None or not self.elastic.enable:
            return False
        if throttled:
            now = time.monotonic()
            if now - self._last_watch < self.elastic.interval:
                return False
            self._last_watch = now
        return self.elastic.watch() in (ElasticStatus.RESTART,
                                        ElasticStatus.ERROR)

    def _classify_failure(self):
        """A step raised: was it a peer death? Poll the elastic verdict
        for up to 2*ttl (a dead peer's beat must age out before the
        watcher can see it) — confirmed death recovers as rank_death
        (membership rebuild), anything else as step_error (restore
        only)."""
        if self.elastic is None or not self.elastic.enable:
            return "step_error"
        deadline = time.monotonic() + 2.0 * self.elastic.ttl
        while time.monotonic() < deadline:
            if self._verdict_bad():
                return "rank_death"
            time.sleep(self.elastic.interval)
        return "step_error"

    def _rebuild_membership(self):
        """Survivors agree on generation g's member set: settle one
        TTL (every watcher must see the same dead set), then run the
        STORE protocol — ``protocol.rebuild_membership``, the ptcheck-
        explored agreement: first-claimant leader election (atomic
        add), newest-COMMON-snapshot intersection, membership publish,
        generation-scoped barrier. Rank ids never renumber. A live
        rank the leader's view missed (heartbeat lagged past ttl)
        finds itself outside the published membership and fails
        CLEANLY instead of half-joining a generation that will not
        wait for it."""
        from . import protocol as _proto

        el = self.elastic
        time.sleep(el.ttl)
        alive = el.alive_nodes()
        dead = sorted(set(el.members) - set(alive))
        self.generation += 1
        gen = self.generation
        base = "%s/resilience/gen%d" % (el.job_id, gen)
        # resume step must be COMMON across survivors; the snapshot
        # list published below is this rank's FULL complete set
        self.flush_snapshots()
        info = _proto.rebuild_membership(
            el.store, base, el.rank, alive, dead,
            list_snapshots(self.snapshot_dir), gen,
            self.store_timeout_s,
            on_members=lambda info: el.set_members(info["members"]))
        if int(info.get("resume_step", -1)) < 0:
            raise RuntimeError(
                "membership rebuild gen %d: survivors %s share no "
                "complete snapshot — a coherent common resume point "
                "does not exist (every rank fails identically here "
                "rather than restoring diverged local states)"
                % (gen, info["members"]))
        if self.on_generation is not None:
            self.on_generation(gen, list(info["members"]), info)
        return info

    def _recover(self, kind, error=None):
        self.recoveries += 1
        if self.recoveries > self.max_recoveries:
            raise RuntimeError(
                "resilience: %d recoveries exceeded max_recoveries=%d "
                "(last trigger %s: %r)"
                % (self.recoveries, self.max_recoveries, kind, error))
        resume_step = None
        if kind == "rank_death":
            info = self._rebuild_membership()
            resume_step = info.get("resume_step")
        restored = self.restore(resume_step)
        RECOVERIES.labels(kind=kind).inc()
        self.recovery_log.append((kind, restored))
        self._recover_requested = None
        return restored

    # -- the loop ---------------------------------------------------------

    def run(self, total_steps):
        """Train to ``total_steps`` global steps, surviving failures.
        Returns the loss trajectory as {step_index: loss} — recovered
        (replayed) steps overwrite their first attempt, so the dict is
        the FINAL trajectory a clean run would pin."""
        losses = {}
        if self.snapshot_every and self.latest_snapshot_step() is None:
            self.snapshot()     # step-0 snapshot: a pre-first-step
            self.flush_snapshots()  # death must have somewhere to resume
        while int(self.train_step._step_count) < total_steps:
            if self._recover_requested:
                self._recover(self._recover_requested)
                continue
            step_i = int(self.train_step._step_count) + 1
            try:
                # steps_per_call > 1: batch_fn returns a stacked
                # [K, ...] window and the whole window runs as ONE
                # device call (run_steps); losses are then pinned per
                # WINDOW at its last step
                if self.steps_per_call > 1:
                    loss = self.train_step.run_steps(
                        *self.batch_fn(step_i))
                else:
                    loss = self.train_step(*self.batch_fn(step_i))
                val = float(np.asarray(
                    loss._value if hasattr(loss, "_value") else loss))
                if self.post_step is not None:
                    val = self.post_step(step_i, val)
            except Exception as e:
                self._recover(self._classify_failure(), error=e)
                continue
            end = int(self.train_step._step_count)
            losses[end] = val
            if self.snapshot_every \
                    and end % self.snapshot_every == 0:
                self.snapshot()
            if self._verdict_bad(throttled=True):
                self._recover("rank_death")
        # a cadence snapshot is SKIPPED when the writer is mid-write
        # (the loop never blocks on disk) — but the END-of-run snapshot
        # must land: it is what a follow-up run resumes from
        self.flush_snapshots()
        end = int(self.train_step._step_count)
        if self.snapshot_every and end % self.snapshot_every == 0 \
                and self.latest_snapshot_step() != end:
            self.snapshot()
            self.flush_snapshots()
        return losses

    def close(self):
        self._writer.stop()
        if getattr(self, "_wd_action", None) is not None:
            from ..monitor import watchdog as _wd

            _wd.unregister_stall_action(self._wd_action)
            self._wd_action = None
