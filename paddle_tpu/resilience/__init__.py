"""paddle_tpu.resilience — deterministic fault injection and
detect→recover→resume across store, training, and serving.

Division of labor with the monitor stack: monitor *names* a failure
(flight recorder → diverging rank, watchdog → stalled bracket, trace →
wedged request); this package *acts* on one:

1. **Fault injection** (resilience/faultinject.py,
   ``FLAGS_fault_inject`` / ``PT_FAULT_SCHEDULE``): seeded,
   schedule-driven faults at named sites threaded through the TCPStore
   ops, the eager collectives, the serving engine, and the compiled
   train step — every recovery path below is exercised reproducibly.

2. **Store hardening** (distributed/store.py): op retry with
   exponential backoff + jitter, automatic reconnect on a dead fd,
   errors naming op/key/peer/attempts, and a reusable round-based
   barrier (restart generations reuse names safely).

3. **ResilientTrainLoop** (resilience/train.py): periodic async
   snapshots off the critical path (distributed/checkpoint format +
   a background writer thread), consuming ``ElasticManager.watch()``
   verdicts to detect a dead rank, rebuild membership over the store,
   and resume from the last complete snapshot with a pinned loss
   trajectory. Registers as a watchdog escalation target
   (``PT_WATCHDOG_ACTION=recover``).

4. **Serving graceful degradation** (serving/engine.py): per-request
   queue-TTL deadlines (``expired`` terminal status), bounded
   admission queue with load shedding, a preemption-count cap,
   poison-request quarantine (a step exception fails the one request,
   not the engine), and ``Engine.drain()`` — the fleet
   drain-and-reschedule building block.

Metrics (the one registry): ``faults_injected_total{site,kind}``,
``recoveries_total{kind}``, ``snapshot_seconds``,
``serving_requests_shed_total{reason}``, ``store_reconnects_total``,
``store_op_retries_total{op}``. Served live at
``GET /debugz/resilience``.

Import discipline: this ``__init__`` (and faultinject) stays
stdlib-only so the store/worker processes can import the injection
sites without an accelerator backend; ``ResilientTrainLoop`` (which
needs jax via the checkpoint layer) loads lazily on first attribute
access.
"""
from __future__ import annotations

from . import faultinject  # noqa: F401  (stdlib-only, always safe)
from .faultinject import InjectedFault  # noqa: F401

__all__ = ["faultinject", "InjectedFault", "ResilientTrainLoop",
           "payload"]


def __getattr__(name):
    # lazy: resilience.train imports the checkpoint layer (jax) — the
    # stdlib-only importers (store.py, bare workers) must not pay it
    if name == "ResilientTrainLoop":
        from .train import ResilientTrainLoop

        return ResilientTrainLoop
    if name == "train":
        from . import train

        return train
    raise AttributeError(name)


def payload():
    """JSON-ready /debugz/resilience payload: injection state plus the
    resilience counters already in the registry snapshot."""
    from ..monitor import registry as _mreg

    reg = _mreg.get_registry()
    counters = {}
    for mname in ("faults_injected_total", "recoveries_total",
                  "snapshots_total", "snapshot_errors_total",
                  "serving_requests_shed_total",
                  "store_reconnects_total", "store_op_retries_total"):
        m = reg.get(mname)
        if m is None:
            continue
        counters[mname] = [
            {"labels": dict(zip(m.labelnames, key)), "value": v}
            for key, v in m.collect()]
    out = {
        "fault_injection": faultinject.state(),
        "counters": counters,
    }
    try:
        from ..monitor import watchdog as _wd

        out["watchdog_action"] = _wd.stall_action()
    except ImportError:
        pass    # monitor stack absent: state() reports without it
    return out
