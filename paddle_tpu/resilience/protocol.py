"""The store-level agreement protocols, isolated from the train loop.

``ResilientTrainLoop`` recovery is two layers: HOST-side mechanics
(settle a TTL, flush snapshots, restore state) and a pure STORE
protocol (publish, claim, agree, barrier). This module is the store
layer on its own, taking the store as an injected parameter — which
is what lets ptcheck (``paddle_tpu/analysis/proto``) drive the REAL
agreement code under a deterministic scheduler and explore every
interleaving and crash point, instead of checking a hand-written
model that drifts from the shipped protocol.

Leader claim: one atomic counter add per generation — the FIRST
survivor to observe 1 leads; the store's nonce-idempotent add keeps
that claim exact even when a lost ack forces a client retry (a
double-applied retry would leave NO rank observing 1 and the
generation leaderless: the historical ``add`` retry hole, now a
ptcheck regression fixture).
"""
from __future__ import annotations

import json


def rebuild_membership(store, base, rank, alive, dead, snapshot_steps,
                       generation, timeout_s, on_members=None):
    """Survivors agree on generation ``generation``'s member set and
    resume step under the key namespace ``base``.

    Protocol (every call sees the same store, injected):

    1. each survivor publishes its FULL complete-snapshot list under
       ``<base>/snap/<rank>`` (retention pruning + skipped writes make
       per-rank sets diverge — a min over latests could name a step
       some rank already pruned);
    2. the FIRST survivor to claim the generation's leader counter
       (one atomic store add — two survivors with momentarily
       different alive views can never both lead) intersects the
       published lists and publishes members + the newest COMMON
       snapshot step under ``<base>/members``;
    3. everyone blocks on the published membership; a rank that finds
       itself outside it fails CLEANLY instead of half-joining a
       generation that will not wait for it;
    4. ``on_members(info)`` runs before the barrier (the caller
       shrinks its watch set here), then everyone barriers on the
       generation-scoped name — safe to reuse across generations and
       across a SHRUNK world: the round-based barrier namespaces its
       counters per (name, world_size).

    Returns the published ``info`` dict. Raises RuntimeError when the
    leader never published within ``timeout_s`` (it died between
    claim and publish) or when this rank is outside the membership.
    """
    store.set("%s/snap/%d" % (base, rank),
              json.dumps(sorted(int(s) for s in snapshot_steps)))
    if store.add(base + "/leader", 1) == 1:
        common = None
        for r in alive:
            data = store.get("%s/snap/%d" % (base, r),
                             timeout_s=timeout_s)
            steps = set() if data is None \
                else set(json.loads(data.decode()))
            common = steps if common is None else (common & steps)
        info = {"members": list(alive), "dead": list(dead),
                "resume_step": max(common) if common else -1,
                "generation": generation}
        store.set(base + "/members", json.dumps(info))
    data = store.get(base + "/members", timeout_s=timeout_s)
    if data is None:
        raise RuntimeError(
            "membership rebuild gen %d: leader never published %r"
            % (generation, base + "/members"))
    info = json.loads(data.decode())
    if rank not in info["members"]:
        raise RuntimeError(
            "membership rebuild gen %d: this rank (%d) is not in "
            "the published membership %s — the leader's liveness "
            "view aged it out; failing cleanly instead of joining "
            "a generation that will not wait for it"
            % (generation, rank, info["members"]))
    if on_members is not None:
        on_members(info)
    store.barrier(base + "/barrier", len(info["members"]),
                  timeout_s=timeout_s)
    return info
