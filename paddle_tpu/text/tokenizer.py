"""In-graph-style BERT tokenization over StringTensor.

Parity: reference faster_tokenizer op
(paddle/fluid/operators/string/faster_tokenizer_op.h: BasicTokenizer,
WordPieceTokenizer, BertTokenizer, FasterTokenizerKernel) — text to
(input_ids, token_type_ids) without a Python preprocessing dependency.

TPU mapping: tokenization is host work in both stacks (the reference
kernel is CPU-only); the output lands directly as device int32 tensors,
padded/truncated to a static max_seq_len so downstream jit sees ONE
shape. Standard public BERT wordpiece algorithm, fresh implementation.
"""
from __future__ import annotations

import unicodedata

import numpy as np

import jax.numpy as jnp

from ..core.string_tensor import StringTensor
from ..core.tensor import Tensor
from ..nn.layer import Layer


def _is_whitespace(ch):
    return ch in " \t\n\r" or unicodedata.category(ch) == "Zs"


def _is_control(ch):
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch):
    cp = ord(ch)
    # ASCII ranges the reference treats as punctuation even when unicode
    # says otherwise (e.g. '$', '`')
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(ch):
    cp = ord(ch)
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
            or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class BasicTokenizer:
    """Whitespace/punctuation/CJK splitting + optional lowercase/accent
    strip (reference faster_tokenizer_op.h:45)."""

    def __init__(self, do_lower_case=True):
        self.do_lower_case = do_lower_case

    def tokenize(self, text):
        # clean: drop control chars, normalize whitespace
        out = []
        for ch in text:
            if ord(ch) == 0 or ord(ch) == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        text = "".join(out)
        # pad CJK chars so each is its own token
        text = "".join(" %s " % ch if _is_cjk(ch) else ch for ch in text)
        tokens = []
        for tok in text.split():
            if self.do_lower_case:
                tok = tok.lower()
                tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                              if unicodedata.category(c) != "Mn")
            # split on punctuation
            cur = []
            for ch in tok:
                if _is_punctuation(ch):
                    if cur:
                        tokens.append("".join(cur))
                        cur = []
                    tokens.append(ch)
                else:
                    cur.append(ch)
            if cur:
                tokens.append("".join(cur))
        return tokens


class WordPieceTokenizer:
    """Greedy longest-match-first subword split (reference
    faster_tokenizer_op.h:56)."""

    def __init__(self, vocab, unk_token="[UNK]",
                 max_input_chars_per_word=100):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, word):
        if len(word) > self.max_input_chars_per_word:
            return [self.unk_token]
        pieces = []
        start = 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    cur = sub
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            pieces.append(cur)
            start = end
        return pieces


class BertTokenizer:
    """Full BERT encode pipeline (reference faster_tokenizer_op.h:70)."""

    def __init__(self, vocab, do_lower_case=True, unk_token="[UNK]",
                 pad_token="[PAD]", cls_token="[CLS]", mask_token="[MASK]",
                 sep_token="[SEP]"):
        self.vocab = dict(vocab)
        self.inv_vocab = {v: k for k, v in self.vocab.items()}
        self.do_lower_case = do_lower_case
        self.unk_token, self.pad_token = unk_token, pad_token
        self.cls_token, self.sep_token = cls_token, sep_token
        self.mask_token = mask_token
        self.basic = BasicTokenizer(do_lower_case)
        self.wordpiece = WordPieceTokenizer(self.vocab, unk_token)
        self.unk_token_id = self.vocab[unk_token]
        self.pad_token_id = self.vocab[pad_token]
        self.cls_token_id = self.vocab[cls_token]
        self.sep_token_id = self.vocab[sep_token]

    def tokenize(self, text):
        toks = []
        for word in self.basic.tokenize(text):
            toks.extend(self.wordpiece.tokenize(word))
        return toks

    def convert_tokens_to_ids(self, tokens):
        return [self.vocab.get(t, self.unk_token_id) for t in tokens]

    def convert_ids_to_tokens(self, ids):
        return [self.inv_vocab.get(int(i), self.unk_token) for i in ids]

    def num_special_tokens_to_add(self, pair=False):
        return 3 if pair else 2

    def build_inputs_with_special_tokens(self, ids0, ids1=None):
        out = [self.cls_token_id] + list(ids0) + [self.sep_token_id]
        if ids1:
            out += list(ids1) + [self.sep_token_id]
        return out

    def create_token_type_ids_from_sequences(self, ids0, ids1=None):
        tt = [0] * (len(ids0) + 2)
        if ids1:
            tt += [1] * (len(ids1) + 1)
        return tt

    def truncate_sequence(self, ids, pair_ids=None, num_tokens_to_remove=0):
        """Longest-first truncation (reference TruncateSequence)."""
        for _ in range(num_tokens_to_remove):
            if pair_ids and len(pair_ids) >= len(ids):
                pair_ids.pop()
            elif ids:
                ids.pop()
        return ids, pair_ids

    def encode(self, text, text_pair=None, max_seq_len=0,
               pad_to_max_seq_len=False, is_split_into_words=False):
        """-> {"input_ids": [...], "token_type_ids": [...]}
        (reference Encode, faster_tokenizer_op.h:96)."""
        def to_ids(t):
            if is_split_into_words:
                # pre-tokenized words: wordpiece only, no basic re-split
                toks = []
                words = t if isinstance(t, (list, tuple)) else t.split()
                for w in words:
                    toks.extend(self.wordpiece.tokenize(w))
                return self.convert_tokens_to_ids(toks)
            return self.convert_tokens_to_ids(self.tokenize(t))

        ids = to_ids(text)
        pair_ids = to_ids(text_pair) if text_pair else None
        n_special = self.num_special_tokens_to_add(pair=bool(pair_ids))
        if max_seq_len:
            total = len(ids) + (len(pair_ids) if pair_ids else 0) + n_special
            if total > max_seq_len:
                ids, pair_ids = self.truncate_sequence(
                    ids, pair_ids, total - max_seq_len)
            if pair_ids is not None and not pair_ids:
                # truncation consumed the whole pair: re-budget as a
                # single sequence (2 specials, not 3) so the output fills
                # max_seq_len instead of leaving a phantom [SEP] slot
                pair_ids = None
                if len(ids) + 2 > max_seq_len:
                    ids = ids[:max_seq_len - 2]
        input_ids = self.build_inputs_with_special_tokens(ids, pair_ids)
        token_type_ids = self.create_token_type_ids_from_sequences(
            ids, pair_ids)
        if max_seq_len and pad_to_max_seq_len:
            pad = max_seq_len - len(input_ids)
            input_ids += [self.pad_token_id] * pad
            token_type_ids += [0] * pad
        return {"input_ids": input_ids, "token_type_ids": token_type_ids}

    def batch_encode(self, texts, text_pairs=None, max_seq_len=0,
                     pad_to_max_seq_len=False, is_split_into_words=False):
        pairs = text_pairs if text_pairs is not None else [None] * len(texts)
        if len(pairs) != len(texts):
            raise ValueError(
                "batch_encode: %d texts vs %d text_pairs"
                % (len(texts), len(pairs)))
        return [self.encode(t, p, max_seq_len, pad_to_max_seq_len,
                            is_split_into_words=is_split_into_words)
                for t, p in zip(texts, pairs)]


class FasterTokenizer(Layer):
    """Layer form (reference FasterTokenizerKernel + the to_static path in
    test_faster_tokenizer_op.py): StringTensor/str batch in →
    (input_ids, token_type_ids) int32 device tensors, padded to the batch
    max (or a fixed max_seq_len so jit sees one shape)."""

    def __init__(self, vocab, do_lower_case=True, is_split_into_words=False,
                 max_seq_len=0, pad_to_max_seq_len=False):
        super().__init__()
        self.tokenizer = BertTokenizer(vocab, do_lower_case=do_lower_case)
        self.is_split_into_words = is_split_into_words
        self.max_seq_len = max_seq_len
        self.pad_to_max_seq_len = pad_to_max_seq_len

    def forward(self, text, text_pair=None):
        def to_list(t):
            if t is None:
                return None
            if isinstance(t, StringTensor):
                return [v if isinstance(v, str) else v.decode("utf-8")
                        for v in np.asarray(t.numpy()).ravel().tolist()]
            if isinstance(t, str):
                return [t]
            return list(t)

        texts = to_list(text)
        pairs = to_list(text_pair)
        if not texts:
            z = jnp.zeros((0, self.max_seq_len), jnp.int32)
            return Tensor(z), Tensor(z)
        enc = self.tokenizer.batch_encode(
            texts, pairs, self.max_seq_len, self.pad_to_max_seq_len,
            is_split_into_words=self.is_split_into_words)
        width = max(len(e["input_ids"]) for e in enc)
        pad_id = self.tokenizer.pad_token_id
        ids = np.full((len(enc), width), pad_id, np.int32)
        tt = np.zeros((len(enc), width), np.int32)
        for i, e in enumerate(enc):
            n = len(e["input_ids"])
            ids[i, :n] = e["input_ids"]
            tt[i, :n] = e["token_type_ids"]
        return Tensor(jnp.asarray(ids)), Tensor(jnp.asarray(tt))
