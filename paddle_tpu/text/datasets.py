"""NLP datasets (reference python/paddle/text/datasets/: conll05.py,
imdb.py, imikolov.py, movielens.py, uci_housing.py, wmt14.py, wmt16.py).
Each reads the reference's on-disk format when a local path is given and
falls back to a deterministic synthetic corpus (zero-egress environment) —
shapes, dtypes and field layouts match the reference loaders.
"""
from __future__ import annotations

import zlib

import numpy as np

from ..io import Dataset


class _SyntheticTokens:
    """Deterministic token-id sequences, one rng per (name, mode)."""

    def __init__(self, name, mode, size, vocab_size, seq_len):
        rng = np.random.RandomState(
            zlib.crc32(("%s/%s" % (name, mode)).encode()) % (2 ** 31))
        self.lens = rng.randint(max(2, seq_len // 2), seq_len + 1, size)
        self.seqs = [rng.randint(1, vocab_size, n).astype(np.int64)
                     for n in self.lens]
        self.rng = rng


class Imdb(Dataset):
    """Sentiment classification: (tokens int64[], label int64 in {0,1})
    (reference text/datasets/imdb.py)."""

    def __init__(self, data_file=None, mode="train", cutoff=150, size=64,
                 vocab_size=512, seq_len=32):
        s = _SyntheticTokens("imdb", mode, size, vocab_size, seq_len)
        self.docs = s.seqs
        self.labels = (s.rng.rand(size) < 0.5).astype(np.int64)
        self.word_idx = {("w%d" % i): i for i in range(vocab_size)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM tuples (reference imikolov.py): returns n-1
    context tokens + next token when data_type='NGRAM'."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, size=128, vocab_size=256):
        self.window_size = window_size
        self.data_type = data_type
        s = _SyntheticTokens("imikolov", mode, size, vocab_size,
                             window_size * 3)
        self.data = []
        for seq in s.seqs:
            if data_type.upper() == "NGRAM":
                for i in range(len(seq) - window_size + 1):
                    self.data.append(tuple(seq[i:i + window_size]))
            else:
                self.data.append(seq)
        self.word_idx = {("w%d" % i): i for i in range(vocab_size)}

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """13 features -> price (reference uci_housing.py). Reads the UCI
    whitespace format when given a file."""

    FEATURE_DIM = 13

    def __init__(self, data_file=None, mode="train", size=128):
        if data_file:
            raw = np.loadtxt(data_file).astype(np.float32)
            feats, prices = raw[:, :-1], raw[:, -1:]
            # reference normalizes by train-split max/min/avg
            mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
            feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            feats = rng.randn(size, self.FEATURE_DIM).astype(np.float32)
            w = rng.randn(self.FEATURE_DIM, 1).astype(np.float32)
            prices = (feats @ w + 0.1 * rng.randn(size, 1)).astype(np.float32)
        split = int(len(feats) * 0.8)
        if mode == "train":
            self.data, self.label = feats[:split], prices[:split]
        else:
            self.data, self.label = feats[split:], prices[split:]

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """SRL dataset (reference conll05.py): 8 int64 feature sequences +
    label sequence per sample."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train",
                 size=32, vocab_size=128, num_labels=18, seq_len=16):
        s = _SyntheticTokens("conll05", mode, size, vocab_size, seq_len)
        self.samples = []
        for seq in s.seqs:
            n = len(seq)
            feats = [seq] + [
                s.rng.randint(1, vocab_size, n).astype(np.int64)
                for _ in range(7)]
            labels = s.rng.randint(0, num_labels, n).astype(np.int64)
            self.samples.append(tuple(feats) + (labels,))
        self.word_dict = {("w%d" % i): i for i in range(vocab_size)}
        self.label_dict = {("l%d" % i): i for i in range(num_labels)}

    def get_dict(self):
        return self.word_dict, self.word_dict, self.label_dict

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """Rating prediction (reference movielens.py): user/movie categorical
    features + float rating."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, size=256):
        rng = np.random.RandomState(rand_seed)
        n_users, n_movies = 100, 200
        users = rng.randint(0, n_users, size).astype(np.int64)
        movies = rng.randint(0, n_movies, size).astype(np.int64)
        genders = (users % 2).astype(np.int64)
        ages = (users % 7).astype(np.int64)
        jobs = (users % 21).astype(np.int64)
        categories = (movies % 18).astype(np.int64)
        titles = rng.randint(1, 64, (size, 8)).astype(np.int64)
        ratings = (1.0 + 4.0 * rng.rand(size)).astype(np.float32)
        is_test = rng.rand(size) < test_ratio
        keep = ~is_test if mode == "train" else is_test
        self.fields = [f[keep] for f in
                       (users, genders, ages, jobs, movies, categories)]
        self.titles = titles[keep]
        self.ratings = ratings[keep]

    def __getitem__(self, idx):
        return tuple(f[idx] for f in self.fields) + (
            self.titles[idx], self.ratings[idx])

    def __len__(self):
        return len(self.ratings)


class _TranslationPairs(Dataset):
    """(src_ids, trg_ids, trg_ids_next) int64 triplets with <s>/<e>/<unk>
    reserved as 0/1/2 (reference wmt14.py/wmt16.py layout)."""

    name = "wmt"
    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_file=None, mode="train", src_dict_size=256,
                 trg_dict_size=256, lang="en", size=64, seq_len=12):
        s = _SyntheticTokens(self.name + lang, mode, size,
                             min(src_dict_size, 256) - 3, seq_len)
        self.pairs = []
        for seq in s.seqs:
            src = seq + 3  # skip reserved ids
            trg = (s.rng.randint(
                3, min(trg_dict_size, 256), len(seq))).astype(np.int64)
            trg_in = np.concatenate([[self.BOS], trg])
            trg_next = np.concatenate([trg, [self.EOS]])
            self.pairs.append((src, trg_in, trg_next))
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size

    def __getitem__(self, idx):
        return self.pairs[idx]

    def __len__(self):
        return len(self.pairs)


class WMT14(_TranslationPairs):
    name = "wmt14"


class WMT16(_TranslationPairs):
    name = "wmt16"
