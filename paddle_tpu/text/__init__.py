"""paddle.text (reference python/paddle/text/__init__.py: ViterbiDecoder /
viterbi_decode + 7 NLP datasets). Zero-egress: datasets read local files
when given paths and otherwise generate deterministic synthetic corpora
with the reference's shapes/dtypes (same pattern as vision/audio).
"""
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401
from .tokenizer import (  # noqa: F401
    BasicTokenizer,
    BertTokenizer,
    FasterTokenizer,
    WordPieceTokenizer,
)
from .datasets import (  # noqa: F401
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)

__all__ = [
    "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
    "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode",
]
