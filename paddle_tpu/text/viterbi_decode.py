"""Viterbi decoding (reference python/paddle/text/viterbi_decode.py:25 and
the phi viterbi_decode kernel). Forward max-sum runs as a vectorized
host-side DP over [B, T, N] emissions — decode is a post-processing step in
the reference too (CPU kernel for CRF inference), not a training hot path.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Returns (scores [B], paths [B, max_len] int64).

    With include_bos_eos_tag=True the transition matrix's last row/column
    act as the start tag and its second-to-last row/column as the stop tag
    (reference semantics).
    """
    import paddle_tpu as paddle

    pots = _np(potentials).astype(np.float64)  # [B, T, N]
    trans = _np(transition_params).astype(np.float64)  # [N, N]
    lens = _np(lengths).astype(np.int64)  # [B]
    B, T, N = pots.shape
    max_len = int(lens.max()) if B else 0

    alpha = pots[:, 0].copy()  # [B, N]
    if include_bos_eos_tag:
        alpha += trans[-1][None, :]  # start -> tag
    history = np.zeros((max(max_len - 1, 0), B, N), np.int64)
    for t in range(1, max_len):
        # scores[b, i, j] = alpha[b, i] + trans[i, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = scores.argmax(axis=1)  # [B, N]
        new_alpha = scores.max(axis=1) + pots[:, t]
        active = (t < lens)[:, None]
        history[t - 1] = np.where(active, best_prev,
                                  np.arange(N)[None, :])
        alpha = np.where(active, new_alpha, alpha)
    final = alpha.copy()
    if include_bos_eos_tag:
        final += trans[:, -2][None, :]  # tag -> stop
    scores = final.max(axis=1)
    last_tag = final.argmax(axis=1)  # [B]

    paths = np.zeros((B, max_len), np.int64)
    if max_len:
        for b in range(B):
            L = int(lens[b])
            if L <= 0:  # zero-length sequence: empty path, no backtrace
                continue
            tag = int(last_tag[b])
            paths[b, L - 1] = tag
            for t in range(L - 2, -1, -1):
                tag = int(history[t, b, tag])
                paths[b, t] = tag
    return (paddle.to_tensor(scores.astype(_np(potentials).dtype)),
            paddle.to_tensor(paths))


class ViterbiDecoder(Layer):
    """reference text/viterbi_decode.py ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
