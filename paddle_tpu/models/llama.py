"""Llama-family decoder — the flagship distributed config (BASELINE.md:
GPT/Llama-7B TP+PP hybrid, tokens/sec/chip).

TPU-native design decisions:
- weights bf16, RMSNorm/softmax statistics fp32 (MXU-native mixed precision)
- attention via F.scaled_dot_product_attention → Pallas flash kernel on TPU
- TP via Column/RowParallelLinear sharding specs ('mp' axis): q/k/v/gate/up
  column-split, o/down row-split — the Megatron layout the reference builds
  from c_split/c_concat ops (fleet/layers/mpu/mp_layers.py)
- sequence axis carries a 'sep' sharding constraint for long-context
  (ring attention in paddle_tpu/kernels/ring_attention.py)
- the decode cache is functional (returned, not mutated) so the generation
  loop jits into one XLA while-loop
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .. import ops
from ..core.dispatch import primitive
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer import Layer
from ..nn.layers.common import Embedding
from ..nn.layers.container import LayerList
from ..nn.layers.norm import RMSNorm
from .generation import (
    DecodeCache,
    GenerationMixin,
    cache_update,
    decode_mask as _decode_mask,
    masked_decode_attention,
)
from ..parallel.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    mark_sharding,
)


class LlamaConfig:
    def __init__(self, vocab_size=32000, hidden_size=4096,
                 intermediate_size=11008, num_hidden_layers=32,
                 num_attention_heads=32, num_key_value_heads=None,
                 max_position_embeddings=4096, rms_norm_eps=1e-6,
                 rope_theta=10000.0, tie_word_embeddings=False,
                 use_parallel=True, dtype="float32",
                 fuse_attention_qkv=False, fuse_mlp=False,
                 sequence_parallel=False, recompute=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads or num_attention_heads
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.tie_word_embeddings = tie_word_embeddings
        self.use_parallel = use_parallel
        self.dtype = dtype
        # MXU shape optimization (reference incubate fused_attention /
        # fused_feedforward analog): one [h, (q+k+v)] and one [h, 2*ffn]
        # matmul instead of 3+2 narrow ones — at hidden sizes where K/N <
        # ~1024 the wider N keeps the systolic array fed (measured on v5e:
        # K=N=768 sustains ~34 TF/s, N=2304 nearly doubles that).
        self.fuse_attention_qkv = fuse_attention_qkv
        self.fuse_mlp = fuse_mlp
        # long-context: shard the sequence axis over 'sep' and run ring
        # attention (kernels/ring_attention.py) — capability the
        # reference snapshot lacks (SURVEY §5)
        self.sequence_parallel = sequence_parallel
        # activation recompute per decoder layer (reference fleet
        # recompute / --recompute flag): trades ~1/3 extra FLOPs for
        # O(layers * B*S*H) activation memory — required to train ~1B+
        # params on one 16GB v5e chip
        self.recompute = recompute

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 max_position_embeddings=128)
        d.update(kw)
        return cls(**d)

    @classmethod
    def llama_7b(cls, **kw):
        return cls(**kw)


@primitive
def rope_apply(q, k, theta, position_offset=0):
    """Rotary position embedding, fused on q and k. q,k: [B, S, H, D].

    Half-split ("rotate half") pairing: dim i rotates with dim i + D/2.
    On TPU this lowers to two contiguous lane slices + concat instead of
    the strided even/odd gather of the interleaved convention — measured
    3x faster fwd+bwd at the bench shape (8x1024x6x128) for identical
    positional geometry (the pairing of dims is a convention, not
    semantics; attention scores are invariant to which pairing is used
    as long as q and k share it).

    position_offset may be a scalar (one offset for the whole batch —
    training/generate) or a [B] int vector (per-row offsets — the
    serving engine's continuous-batching decode, where every slot sits
    at its own sequence position)."""
    q = jnp.asarray(q)
    k = jnp.asarray(k)
    d = q.shape[-1]
    seq = q.shape[1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    off = (position_offset if isinstance(position_offset, (int, float))
           else jnp.asarray(position_offset))
    if getattr(off, "ndim", 0):
        # per-row offsets: pos [B, S] -> freqs [B, S, D/2], cos/sin
        # [B, S, 1, D] (elementwise identical to the scalar path per row)
        pos = (off.astype(jnp.float32)[:, None]
               + jnp.arange(seq, dtype=jnp.float32)[None, :])
        freqs = pos[..., None] * inv_freq
        cos = jnp.concatenate([jnp.cos(freqs), jnp.cos(freqs)],
                              axis=-1)[:, :, None, :]
        sin = jnp.concatenate([jnp.sin(freqs), jnp.sin(freqs)],
                              axis=-1)[:, :, None, :]
        return _rope_rot(q, cos, sin), _rope_rot(k, cos, sin)
    pos = jnp.arange(seq, dtype=jnp.float32) + off
    freqs = jnp.outer(pos, inv_freq)  # [S, D/2]
    cos = jnp.concatenate([jnp.cos(freqs), jnp.cos(freqs)],
                          axis=-1)[None, :, None, :]
    sin = jnp.concatenate([jnp.sin(freqs), jnp.sin(freqs)],
                          axis=-1)[None, :, None, :]

    return _rope_rot(q, cos, sin), _rope_rot(k, cos, sin)


def _rope_rot(x, cos, sin):
    d = x.shape[-1]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :d // 2], xf[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (xf * cos + rotated * sin).astype(x.dtype)


class LlamaAttention(Layer):
    def __init__(self, config):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.num_kv_heads = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.rope_theta = c.rope_theta
        self.sequence_parallel = c.sequence_parallel
        self.fuse_qkv = c.fuse_attention_qkv and not c.use_parallel
        if self.fuse_qkv:
            from ..nn.layers.common import Linear

            q_dim = self.num_heads * self.head_dim
            kv_dim = self.num_kv_heads * self.head_dim
            self._qkv_splits = (q_dim, q_dim + kv_dim)
            self.qkv_proj = Linear(c.hidden_size, q_dim + 2 * kv_dim,
                                   bias_attr=False)
            self.o_proj = Linear(q_dim, c.hidden_size, bias_attr=False)
        elif c.use_parallel:
            self.q_proj = ColumnParallelLinear(
                c.hidden_size, self.num_heads * self.head_dim,
                has_bias=False, gather_output=False)
            self.k_proj = ColumnParallelLinear(
                c.hidden_size, self.num_kv_heads * self.head_dim,
                has_bias=False, gather_output=False)
            self.v_proj = ColumnParallelLinear(
                c.hidden_size, self.num_kv_heads * self.head_dim,
                has_bias=False, gather_output=False)
            self.o_proj = RowParallelLinear(
                self.num_heads * self.head_dim, c.hidden_size,
                has_bias=False, input_is_parallel=True)
        else:
            from ..nn.layers.common import Linear

            self.q_proj = Linear(c.hidden_size,
                                 self.num_heads * self.head_dim,
                                 bias_attr=False)
            self.k_proj = Linear(c.hidden_size,
                                 self.num_kv_heads * self.head_dim,
                                 bias_attr=False)
            self.v_proj = Linear(c.hidden_size,
                                 self.num_kv_heads * self.head_dim,
                                 bias_attr=False)
            self.o_proj = Linear(self.num_heads * self.head_dim,
                                 c.hidden_size, bias_attr=False)

    def forward(self, x, cache=None, position_offset=0):
        b, s, _ = x.shape
        if self.fuse_qkv:
            qkv = self.qkv_proj(x)
            s1, s2 = self._qkv_splits
            q = qkv[:, :, :s1].reshape([b, s, self.num_heads, self.head_dim])
            k = qkv[:, :, s1:s2].reshape(
                [b, s, self.num_kv_heads, self.head_dim])
            v = qkv[:, :, s2:].reshape(
                [b, s, self.num_kv_heads, self.head_dim])
        else:
            q = self.q_proj(x).reshape(
                [b, s, self.num_heads, self.head_dim])
            k = self.k_proj(x).reshape(
                [b, s, self.num_kv_heads, self.head_dim])
            v = self.v_proj(x).reshape(
                [b, s, self.num_kv_heads, self.head_dim])
        q, k = rope_apply(q, k, theta=self.rope_theta,
                          position_offset=position_offset)
        if cache is not None and hasattr(cache, "update_and_attend"):
            # external-cache hook (serving): the ENGINE owns a paged KV
            # cache; the per-layer view writes this step's K/V into its
            # pool pages and runs ragged paged attention (GQA repeat
            # happens inside the view/kernel — the pool never stores
            # repeated heads). serving/kv_cache.py.
            ctx, cache = cache.update_and_attend(q, k, v)
            out = ctx.reshape([b, s, self.num_heads * self.head_dim])
            return self.o_proj(out), cache
        mask = None
        if isinstance(cache, DecodeCache):
            # static-buffer decode path (generation.py): ONE compiled
            # shape for the whole generation, no concat-regrow recompiles
            cache, k, v = cache_update(cache, k, v, position_offset)
            mask = _decode_mask(position_offset, s, k.shape[1])
        elif cache is not None:
            pk, pv = cache
            k = ops.manipulation.concat([pk, k], axis=1)
            v = ops.manipulation.concat([pv, v], axis=1)
            cache = (k, v)
            # end-aligned: the s new queries sit at the END of the kv
            # window (one shared masking convention — generation.py)
            mask = _decode_mask(k.shape[1] - s, s, k.shape[1])
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            k = ops.manipulation.repeat_interleave(k, rep, axis=2)
            v = ops.manipulation.repeat_interleave(v, rep, axis=2)
        if self.sequence_parallel and cache is None:
            # ring attention over the 'sep' axis (falls back to flash
            # attention when the mesh has no sep axis)
            out = F.sequence_parallel_attention(q, k, v, is_causal=True)
        elif mask is not None:
            out = masked_decode_attention(q, k, v, mask)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if cache is not None:
            return out, cache
        return out


class LlamaMLP(Layer):
    def __init__(self, config):
        super().__init__()
        c = config
        self.fuse_mlp = c.fuse_mlp and not c.use_parallel
        if self.fuse_mlp:
            from ..nn.layers.common import Linear

            self._inter = c.intermediate_size
            self.gate_up_proj = Linear(c.hidden_size,
                                       2 * c.intermediate_size,
                                       bias_attr=False)
            self.down_proj = Linear(c.intermediate_size, c.hidden_size,
                                    bias_attr=False)
        elif c.use_parallel:
            self.gate_proj = ColumnParallelLinear(
                c.hidden_size, c.intermediate_size, has_bias=False,
                gather_output=False)
            self.up_proj = ColumnParallelLinear(
                c.hidden_size, c.intermediate_size, has_bias=False,
                gather_output=False)
            self.down_proj = RowParallelLinear(
                c.intermediate_size, c.hidden_size, has_bias=False,
                input_is_parallel=True)
        else:
            from ..nn.layers.common import Linear

            self.gate_proj = Linear(c.hidden_size, c.intermediate_size,
                                    bias_attr=False)
            self.up_proj = Linear(c.hidden_size, c.intermediate_size,
                                  bias_attr=False)
            self.down_proj = Linear(c.intermediate_size, c.hidden_size,
                                    bias_attr=False)

    def forward(self, x):
        if self.fuse_mlp:
            gu = self.gate_up_proj(x)
            gate, up = gu[:, :, :self._inter], gu[:, :, self._inter:]
            return self.down_proj(F.silu(gate) * up)
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cache=None, position_offset=0):
        h = self.input_layernorm(x)
        if cache is not None:
            attn, cache = self.self_attn(h, cache, position_offset)
        else:
            attn = self.self_attn(h)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        if cache is not None:
            return x, cache
        return x


def _remat_layer(layer, x):
    """Per-layer activation recompute. Two engines, one policy (same split
    as static/__init__.py RecomputeContext vs fleet/recompute.py):
    - compiled path (CompiledTrainStep traces under no_grad + jax.grad):
      wrap the layer body in jax.checkpoint so XLA rematerializes its
      activations during the backward schedule;
    - eager-tape path: route through the autograd engine's recompute().
    """
    from ..core.dispatch import tape_enabled

    if tape_enabled():
        from ..distributed.fleet.recompute import recompute

        return recompute(layer, x)
    import jax

    from ..core.tensor import Tensor

    def body(xv, _l=layer):
        return _l(Tensor(xv))._value

    return Tensor(jax.checkpoint(body)(x._value))


class LlamaModel(Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        Emb = VocabParallelEmbedding if config.use_parallel else Embedding
        self.embed_tokens = Emb(config.vocab_size, config.hidden_size)
        self.layers = LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def _sep_spec(self):
        """(batch_axes, 'sep', None) when the mesh has a >1 'sep' axis."""
        if not self.config.sequence_parallel:
            return None
        from ..distributed import mesh as _mesh

        mesh = _mesh.get_mesh()
        if "sep" not in mesh.axis_names or mesh.shape["sep"] <= 1:
            return None
        batch = tuple(a for a in ("dp", "sharding")
                      if a in mesh.axis_names and mesh.shape[a] > 1)
        return (batch if batch else None, "sep", None)

    def forward(self, input_ids, caches=None, position_offset=0):
        x = self.embed_tokens(input_ids)
        # dp on batch, sep on sequence when those axes exist
        spec = self._sep_spec() if caches is None else None
        if spec is not None:
            x = mark_sharding(x, *spec)
        new_caches = []
        use_remat = self.config.recompute and caches is None
        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(x, caches[i], position_offset)
                new_caches.append(c)
            elif use_remat:
                x = _remat_layer(layer, x)
            else:
                x = layer(x)
        x = self.norm(x)
        if caches is not None:
            return x, new_caches
        return x


class LlamaForCausalLM(GenerationMixin, Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.use_parallel:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False)
        else:
            from ..nn.layers.common import Linear

            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def _maybe_fused_ce(self, h, labels):
        """Scalar mean-CE loss via the streaming lm_head+CE kernel
        (kernels/fused_ce.py) when FLAGS_fused_lm_head_ce is on, the
        token count tiles, and we are on a TRACED (compiled-step) path
        — the custom_vjp carries grads through jax.grad but the eager
        tape cannot see through it. h must already be final-normed.
        Returns None when the fused path does not apply."""
        from ..core.tensor import Tensor
        from ..kernels.fused_ce import fused_ce_applies, fused_mean_ce

        hv = h._value if isinstance(h, Tensor) else h
        if not fused_ce_applies(hv, self.config.use_parallel):
            return None
        B, S, H = hv.shape
        lv = labels._value if isinstance(labels, Tensor) \
            else jnp.asarray(labels)
        return Tensor(fused_mean_ce(hv.reshape(B * S, H),
                                    self.lm_head.weight._value,
                                    lv.reshape(B * S)))

    def forward(self, input_ids, labels=None):
        h = self.llama(input_ids)
        if labels is not None:
            fused = self._maybe_fused_ce(h, labels)
            if fused is not None:
                return fused
        logits = self.lm_head(h)
        if labels is not None:
            if self.config.use_parallel:
                # vocab stays mp-sharded through the loss (sharded-vocab
                # c_softmax_with_cross_entropy, mp_layers.py) — no
                # full-vocab gather under the partitioner
                from ..parallel.mp_layers import (
                    parallel_softmax_cross_entropy,
                )

                flat = labels.reshape([-1])
                per_tok = parallel_softmax_cross_entropy(
                    logits.reshape([-1, self.config.vocab_size]), flat)
                # mean over VALID tokens (same contract as the
                # F.cross_entropy branch: ignore_index rows excluded)
                valid = (flat != -100).astype(per_tok.dtype)
                return per_tok.sum() / valid.sum().clip(min=1.0)
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]))
            return loss
        return logits

    def generate_step(self, input_ids, caches, position_offset):
        """Single decode step with functional cache."""
        h, caches = self.llama(input_ids, caches, position_offset)
        logits = self.lm_head(h)
        return logits, caches

    def max_decode_len(self):
        return self.config.max_position_embeddings

    def paged_cache_spec(self):
        """KV geometry for the serving engine's paged cache (the engine
        owns the cache — serving/engine.py)."""
        cfg = self.config
        return {"num_layers": cfg.num_hidden_layers,
                "num_kv_heads": cfg.num_key_value_heads,
                "head_dim": cfg.hidden_size // cfg.num_attention_heads,
                "dtype": cfg.dtype}

    def init_decode_caches(self, batch, total_len):
        cfg = self.config
        n_kv = cfg.num_key_value_heads
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        kv_dtype = jnp.dtype(cfg.dtype)
        return [DecodeCache(
            jnp.zeros((batch, total_len, n_kv, head_dim), kv_dtype),
            jnp.zeros((batch, total_len, n_kv, head_dim), kv_dtype))
            for _ in range(cfg.num_hidden_layers)]

    # -- pipeline-parallel protocol (parallel/pipeline_parallel.py) --------

    def pipeline_blocks(self):
        """The identical decoder blocks the ring pipeline stacks over 'pp'."""
        return list(self.llama.layers)

    def forward_embed(self, input_ids):
        return self.llama.embed_tokens(input_ids)

    def forward_head(self, h):
        return self.lm_head(self.llama.norm(h))

    def forward_head_loss(self, h, labels):
        """Fused pipeline loss tail (mean CE over non-ignored tokens —
        forward(labels=...)'s contract). Returns None so the caller
        falls back to forward_head + its loss_fn when the kernel path
        does not apply. Consulted only under PipelinedTrainStep's
        EXPLICIT fused_loss_tail=True opt-in: it replaces the step's
        loss_fn, which is only valid for the plain-CE objective."""
        return self._maybe_fused_ce(self.llama.norm(h), labels)
