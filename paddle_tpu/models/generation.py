"""Shared autoregressive generation machinery.

Reference analog: PaddleNLP GenerationMixin (greedy/sampling over growing
DenseTensor caches, top_k_top_p sampling ops). TPU-first shape instead:

- `DecodeCache`: static-size per-layer KV buffer (pytree NamedTuple) —
  written with dynamic_update_slice at the position head, ONE compiled
  shape for the whole generation (growing caches would recompile every
  step under XLA).
- `GenerationMixin.generate`: jitted prefill over the prompt (flash
  kernel eligible), then the entire decode loop as a single XLA
  while-loop with eos early-exit.

A model opts in by providing:
  generate_step(input_ids, caches, position_offset) -> (logits, caches)
  init_decode_caches(batch, total_len) -> list[DecodeCache]
  functional_state() / bind_state(...)  (nn.Layer already has these)
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class DecodeCache(NamedTuple):
    """[B, L_max, H_kv, D] static KV buffers for one layer."""

    k: "object"
    v: "object"


def cache_update(cache, k, v, position_offset):
    """Write s new K/V rows into the static buffers at position_offset;
    returns (new_cache, k_full, v_full) with k/v as full-buffer Tensors."""
    import jax

    from ..core.tensor import Tensor

    def _upd(buf, new):
        nv = new._value if hasattr(new, "_value") else jnp.asarray(new)
        return jax.lax.dynamic_update_slice(
            buf, nv.astype(buf.dtype), (0, position_offset, 0, 0))

    kb = _upd(cache.k, k)
    vb = _upd(cache.v, v)
    return DecodeCache(kb, vb), Tensor(kb), Tensor(vb)


def decode_mask(position_offset, s, kv_len):
    """Valid-region causal mask for cached decode, or the string "causal"
    when it reduces to plain start-aligned causality (static prefill at
    offset 0 — lets the flash kernel stay eligible)."""
    if isinstance(position_offset, int) and position_offset == 0:
        return "causal"
    kv_pos = jnp.arange(kv_len)
    q_pos = position_offset + jnp.arange(s)
    return kv_pos[None, :] <= q_pos[:, None]  # [s, kv]


def masked_decode_attention(q, k, v, mask):
    """Dispatch on decode_mask()'s result."""
    from ..nn import functional as F

    if isinstance(mask, str):  # "causal"
        # prefill at offset 0 against a preallocated cache: start-aligned
        # is exactly right (uninitialized tail slots are masked)
        return F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                              _warn_rect_causal=False)
    return F.scaled_dot_product_attention(
        q, k, v, attn_mask=mask[None, None], is_causal=False)


class GenerationMixin:
    def max_decode_len(self):
        """Maximum total sequence length (prompt + generated), or None
        when unbounded. Models override."""
        return None

    def _coerce_prompt(self, input_ids, max_new_tokens):
        """-> (ids int32 [b, prompt_len], b, prompt_len, total); validates
        against max_decode_len (out-of-range positions would clamp in
        XLA's gather for learned position tables, or extrapolate silently
        for rope)."""
        from ..core.tensor import Tensor

        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        b, prompt_len = ids.shape
        total = prompt_len + max_new_tokens
        limit = self.max_decode_len()
        if limit is not None and total > limit:
            raise ValueError(
                "generate: prompt_len (%d) + max_new_tokens (%d) exceeds "
                "the model's maximum sequence length (%d)"
                % (prompt_len, max_new_tokens, limit))
        return ids, b, prompt_len, total

    def _jit_cached(self, cache_key, build, state_names=()):
        """Per-signature compiled-callable cache, bounded at 16 retained
        executables (varying prompt lengths in a serving loop would
        otherwise grow it forever). The functional-state NAMES are part
        of the key: a compiled program binds state positionally against
        the name list it was traced with, so any module-tree mutation
        (e.g. quantization.convert_to_int8 swapping Linear->Int8Linear,
        possibly on a deep copy that inherited this cache) must miss the
        cache instead of mis-binding the new value list."""
        import jax

        cache_key = cache_key + (tuple(state_names),)
        jit_cache = self.__dict__.setdefault("_generate_jit_cache", {})
        compiled = jit_cache.get(cache_key)
        if compiled is None:
            if len(jit_cache) >= 16:
                jit_cache.pop(next(iter(jit_cache)))
            compiled = jax.jit(build())
            jit_cache[cache_key] = compiled
        return compiled

    def _make_step_logits(self, names, state_vals, as_f32=False):
        """One decode step shared by every strategy: bind functional
        state, run generate_step, return last-token logits + caches."""
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        def step_logits(token_ids, caches, offset):
            with self.bind_state(names, list(state_vals)):
                with no_grad():
                    logits, caches = self.generate_step(
                        Tensor(token_ids), caches, offset)
            lv = logits._value if isinstance(logits, Tensor) else logits
            lv = lv[:, -1, :]
            return (lv.astype(jnp.float32) if as_f32 else lv), caches

        return step_logits

    def _run_eval(self, compiled, *args):
        """Invoke a compiled generation program in inference semantics:
        dropout off inside the traced loop (Layer.training defaults True;
        a traced train-mode dropout would corrupt logits with one frozen
        mask per trace), training flag restored after."""
        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        was_training = self.training
        self.eval()
        try:
            with no_grad():
                out = compiled(*args)
        finally:
            if was_training:
                self.train()
        return Tensor(out)

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 top_k=0, top_p=1.0, temperature=1.0, eos_token_id=None,
                 seed=0, num_beams=1, length_penalty=0.0):
        """Autoregressive generation, compiled end to end. Returns the
        generated ids [B, max_new_tokens] (prompt excluded); positions
        after a sequence's eos are padded with eos.

        num_beams > 1 switches to beam search (reference PaddleNLP
        decode_strategy='beam_search'): beams live as an expanded batch
        inside the same compiled while-loop; each step takes the top
        num_beams continuations over (beams x vocab) cumulative
        log-probs, with finished beams frozen on eos. length_penalty is
        the GNMT exponent alpha (score / len^alpha) applied at the final
        beam selection."""
        import jax

        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        if num_beams > 1:
            if do_sample:
                raise ValueError(
                    "beam search is deterministic; do_sample=True "
                    "conflicts with num_beams > 1")
            return self._beam_search(input_ids, max_new_tokens, num_beams,
                                     eos_token_id, length_penalty,
                                     temperature)

        ids, b, prompt_len, total = self._coerce_prompt(
            input_ids, max_new_tokens)
        names, values = self.functional_state()

        def sample(logits, key):
            logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
            if not do_sample:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if top_k:
                kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if top_p < 1.0:
                sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(sorted_l, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # smallest prefix with mass >= top_p stays
                cutoff_idx = jnp.sum(cum < top_p, axis=-1)
                cutoff = jnp.take_along_axis(
                    sorted_l, cutoff_idx[:, None], axis=-1)
                logits = jnp.where(logits < cutoff, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1) \
                .astype(jnp.int32)

        def run(state_vals, ids, key):
            caches = self.init_decode_caches(b, total)
            step_logits = self._make_step_logits(names, state_vals)

            # prefill the whole prompt in one pass
            last, caches = step_logits(ids, caches, 0)
            key, sub = jax.random.split(key)
            tok = sample(last, sub)
            fill = eos_token_id if eos_token_id is not None else 0
            out0 = jnp.full((b, max_new_tokens), fill, jnp.int32) \
                .at[:, 0].set(tok)
            done0 = (tok == eos_token_id) if eos_token_id is not None \
                else jnp.zeros((b,), bool)

            def cond(carry):
                i, tok, caches, out, done, key = carry
                return jnp.logical_and(i < max_new_tokens,
                                       jnp.logical_not(jnp.all(done)))

            def body(carry):
                i, tok, caches, out, done, key = carry
                last, caches = step_logits(tok[:, None], caches,
                                           prompt_len + i - 1)
                key, sub = jax.random.split(key)
                nxt = sample(last, sub)
                if eos_token_id is not None:
                    nxt = jnp.where(done, eos_token_id, nxt)
                    done = jnp.logical_or(done, nxt == eos_token_id)
                out = out.at[:, i].set(nxt)
                return (i + 1, nxt, caches, out, done, key)

            # decode loop: one XLA while_loop (early exit on all-eos)
            _, _, _, out, _, _ = jax.lax.while_loop(
                cond, body, (1, tok, caches, out0, done0, key))
            return out

        compiled = self._jit_cached(
            (b, prompt_len, max_new_tokens, do_sample, top_k, top_p,
             temperature, eos_token_id), lambda: run,
            state_names=names)
        return self._run_eval(compiled, list(values), ids,
                              jax.random.key(seed))

    def _beam_search(self, input_ids, max_new_tokens, num_beams,
                     eos_token_id, length_penalty, temperature):
        import jax

        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        ids, b, prompt_len, total = self._coerce_prompt(
            input_ids, max_new_tokens)
        names, values = self.functional_state()
        K = num_beams
        NEG = jnp.float32(-1e9)

        def run(state_vals, ids):
            step_logits = self._make_step_logits(names, state_vals,
                                                 as_f32=True)

            # prefill ONCE at batch b (beams are byte-identical over the
            # prompt), then fan the caches/logits out to b*K beam rows
            caches = self.init_decode_caches(b, total)
            last, caches = step_logits(ids, caches, 0)
            caches = jax.tree_util.tree_map(
                lambda x: jnp.repeat(x, K, axis=0), caches)
            last = jnp.repeat(last, K, axis=0)           # [b*K, V]
            logp = jax.nn.log_softmax(last / max(temperature, 1e-6), -1)
            vocab = logp.shape[-1]
            # first step: all beams of a batch row are identical — mask
            # beams 1..K-1 so the top-K picks K DISTINCT first tokens
            beam_mask = jnp.where(
                jnp.arange(b * K) % K == 0, 0.0, NEG)[:, None]
            scores0 = (logp + beam_mask).reshape(b, K * vocab)
            top_s, top_i = jax.lax.top_k(scores0, K)     # [b, K]
            tok0 = (top_i % vocab).astype(jnp.int32)
            out0 = jnp.full((b, K, max_new_tokens),
                            eos_token_id if eos_token_id is not None else 0,
                            jnp.int32).at[:, :, 0].set(tok0)
            done0 = ((tok0 == eos_token_id) if eos_token_id is not None
                     else jnp.zeros((b, K), bool))
            # NOTE: beams share the prefill cache rows (identical prompt),
            # so no cache reorder is needed at the first step
            carry0 = (jnp.asarray(1), tok0, caches, out0, top_s, done0)

            def cond(c):
                i, tok, caches, out, scores, done = c
                return jnp.logical_and(i < max_new_tokens,
                                       jnp.logical_not(jnp.all(done)))

            def body(c):
                i, tok, caches, out, scores, done = c
                last, caches = step_logits(
                    tok.reshape(b * K, 1), caches, prompt_len + i - 1)
                logp = jax.nn.log_softmax(
                    last / max(temperature, 1e-6), -1)   # [b*K, V]
                logp = logp.reshape(b, K, vocab)
                if eos_token_id is not None:
                    # finished beams: only eos continues, at zero cost
                    frozen = jnp.full((vocab,), NEG).at[eos_token_id].set(0.0)
                    logp = jnp.where(done[:, :, None], frozen[None, None, :],
                                     logp)
                cand = (scores[:, :, None] + logp).reshape(b, K * vocab)
                scores, idx = jax.lax.top_k(cand, K)     # [b, K]
                src_beam = idx // vocab                  # [b, K]
                nxt = (idx % vocab).astype(jnp.int32)
                # reorder carried state to the winning source beams
                flat_src = (jnp.arange(b)[:, None] * K + src_beam) \
                    .reshape(-1)                         # [b*K]
                caches = jax.tree_util.tree_map(
                    lambda x: x[flat_src], caches)
                out = jnp.take_along_axis(
                    out, src_beam[:, :, None], axis=1)
                done = jnp.take_along_axis(done, src_beam, axis=1)
                if eos_token_id is not None:
                    done = jnp.logical_or(done, nxt == eos_token_id)
                out = out.at[:, :, i].set(nxt)
                return (i + 1, nxt, caches, out, scores, done)

            i, _, _, out, scores, done = jax.lax.while_loop(
                cond, body, carry0)
            # GNMT length normalization at final selection
            if length_penalty:
                lengths = jnp.where(
                    done,
                    jnp.argmax(
                        out == (eos_token_id
                                if eos_token_id is not None else -1),
                        axis=-1) + 1,
                    i).astype(jnp.float32).clip(min=1.0)
                norm = scores / (lengths ** length_penalty)
            else:
                norm = scores
            best = jnp.argmax(norm, axis=1)              # [b]
            return jnp.take_along_axis(
                out, best[:, None, None], axis=1)[:, 0]

        compiled = self._jit_cached(
            ("beam", b, prompt_len, max_new_tokens, K, eos_token_id,
             length_penalty, temperature), lambda: run,
            state_names=names)
        return self._run_eval(compiled, list(values), ids)
