"""Shared autoregressive generation machinery.

Reference analog: PaddleNLP GenerationMixin (greedy/sampling over growing
DenseTensor caches, top_k_top_p sampling ops). TPU-first shape instead:

- `DecodeCache`: static-size per-layer KV buffer (pytree NamedTuple) —
  written with dynamic_update_slice at the position head, ONE compiled
  shape for the whole generation (growing caches would recompile every
  step under XLA).
- `GenerationMixin.generate`: jitted prefill over the prompt (flash
  kernel eligible), then the entire decode loop as a single XLA
  while-loop with eos early-exit.

A model opts in by providing:
  generate_step(input_ids, caches, position_offset) -> (logits, caches)
  init_decode_caches(batch, total_len) -> list[DecodeCache]
  functional_state() / bind_state(...)  (nn.Layer already has these)
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class DecodeCache(NamedTuple):
    """[B, L_max, H_kv, D] static KV buffers for one layer."""

    k: "object"
    v: "object"


def cache_update(cache, k, v, position_offset):
    """Write s new K/V rows into the static buffers at position_offset;
    returns (new_cache, k_full, v_full) with k/v as full-buffer Tensors."""
    import jax

    from ..core.tensor import Tensor

    def _upd(buf, new):
        nv = new._value if hasattr(new, "_value") else jnp.asarray(new)
        return jax.lax.dynamic_update_slice(
            buf, nv.astype(buf.dtype), (0, position_offset, 0, 0))

    kb = _upd(cache.k, k)
    vb = _upd(cache.v, v)
    return DecodeCache(kb, vb), Tensor(kb), Tensor(vb)


def decode_mask(position_offset, s, kv_len):
    """Valid-region causal mask for cached decode, or the string "causal"
    when it reduces to plain start-aligned causality (static prefill at
    offset 0 — lets the flash kernel stay eligible)."""
    if isinstance(position_offset, int) and position_offset == 0:
        return "causal"
    kv_pos = jnp.arange(kv_len)
    q_pos = position_offset + jnp.arange(s)
    return kv_pos[None, :] <= q_pos[:, None]  # [s, kv]


def masked_decode_attention(q, k, v, mask):
    """Dispatch on decode_mask()'s result."""
    from ..nn import functional as F

    if isinstance(mask, str):  # "causal"
        return F.scaled_dot_product_attention(q, k, v, is_causal=True)
    return F.scaled_dot_product_attention(
        q, k, v, attn_mask=mask[None, None], is_causal=False)


class GenerationMixin:
    def max_decode_len(self):
        """Maximum total sequence length (prompt + generated), or None
        when unbounded. Models override."""
        return None

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 top_k=0, top_p=1.0, temperature=1.0, eos_token_id=None,
                 seed=0):
        """Autoregressive generation, compiled end to end. Returns the
        generated ids [B, max_new_tokens] (prompt excluded); positions
        after a sequence's eos are padded with eos."""
        import jax

        from ..core.dispatch import no_grad
        from ..core.tensor import Tensor

        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        ids = ids.astype(jnp.int32)
        b, prompt_len = ids.shape
        total = prompt_len + max_new_tokens
        limit = self.max_decode_len()
        if limit is not None and total > limit:
            # out-of-range positions would clamp in XLA's gather (learned
            # position tables) or extrapolate silently (rope) — refuse
            raise ValueError(
                "generate: prompt_len (%d) + max_new_tokens (%d) exceeds "
                "the model's maximum sequence length (%d)"
                % (prompt_len, max_new_tokens, limit))
        names, values = self.functional_state()

        def sample(logits, key):
            logits = logits.astype(jnp.float32) / max(temperature, 1e-6)
            if not do_sample:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if top_k:
                kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
            if top_p < 1.0:
                sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
                probs = jax.nn.softmax(sorted_l, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                # smallest prefix with mass >= top_p stays
                cutoff_idx = jnp.sum(cum < top_p, axis=-1)
                cutoff = jnp.take_along_axis(
                    sorted_l, cutoff_idx[:, None], axis=-1)
                logits = jnp.where(logits < cutoff, -jnp.inf, logits)
            return jax.random.categorical(key, logits, axis=-1) \
                .astype(jnp.int32)

        def run(state_vals, ids, key):
            caches = self.init_decode_caches(b, total)

            def step_logits(token_ids, caches, offset):
                with self.bind_state(names, list(state_vals)):
                    with no_grad():
                        logits, caches = self.generate_step(
                            Tensor(token_ids), caches, offset)
                lv = logits._value if isinstance(logits, Tensor) else logits
                return lv[:, -1, :], caches

            # prefill the whole prompt in one pass
            last, caches = step_logits(ids, caches, 0)
            key, sub = jax.random.split(key)
            tok = sample(last, sub)
            fill = eos_token_id if eos_token_id is not None else 0
            out0 = jnp.full((b, max_new_tokens), fill, jnp.int32) \
                .at[:, 0].set(tok)
            done0 = (tok == eos_token_id) if eos_token_id is not None \
                else jnp.zeros((b,), bool)

            def cond(carry):
                i, tok, caches, out, done, key = carry
                return jnp.logical_and(i < max_new_tokens,
                                       jnp.logical_not(jnp.all(done)))

            def body(carry):
                i, tok, caches, out, done, key = carry
                last, caches = step_logits(tok[:, None], caches,
                                           prompt_len + i - 1)
                key, sub = jax.random.split(key)
                nxt = sample(last, sub)
                if eos_token_id is not None:
                    nxt = jnp.where(done, eos_token_id, nxt)
                    done = jnp.logical_or(done, nxt == eos_token_id)
                out = out.at[:, i].set(nxt)
                return (i + 1, nxt, caches, out, done, key)

            # decode loop: one XLA while_loop (early exit on all-eos)
            _, _, _, out, _, _ = jax.lax.while_loop(
                cond, body, (1, tok, caches, out0, done0, key))
            return out

        # one compiled program per (shape, sampling-config) signature —
        # repeat serving calls hit the cache instead of re-tracing
        cache_key = (b, prompt_len, max_new_tokens, do_sample, top_k,
                     top_p, temperature, eos_token_id)
        jit_cache = self.__dict__.setdefault("_generate_jit_cache", {})
        compiled = jit_cache.get(cache_key)
        if compiled is None:
            if len(jit_cache) >= 16:
                # bound retained executables: varying prompt lengths in a
                # serving loop would otherwise grow this forever (callers
                # wanting few compiles should pad prompts to buckets)
                jit_cache.pop(next(iter(jit_cache)))
            compiled = jax.jit(run)
            jit_cache[cache_key] = compiled

        # inference semantics: dropout must be off inside the compiled
        # loop (Layer.training defaults True; a traced train-mode dropout
        # would corrupt logits with one frozen mask per trace)
        was_training = self.training
        self.eval()
        try:
            with no_grad():
                out = compiled(list(values), ids, jax.random.key(seed))
        finally:
            if was_training:
                self.train()
        return Tensor(out)
