"""GPT-style decoder (ERNIE/GPT configs; reference ecosystem models built on
paddle.nn.TransformerDecoder). LayerNorm + learned positions + GELU MLP."""
from __future__ import annotations

import jax.numpy as jnp

from .. import ops
from ..nn import functional as F
from .generation import (
    DecodeCache,
    GenerationMixin,
    cache_update,
    decode_mask as _decode_mask,
    masked_decode_attention,
)
from ..nn.layer import Layer
from ..nn.layers.common import Dropout, Embedding, Linear
from ..nn.layers.container import LayerList
from ..nn.layers.norm import LayerNorm
from ..parallel.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)


class GPTBlock(Layer):
    def __init__(self, hidden, heads, ffn, dropout=0.0, use_parallel=False,
                 moe_experts=0, moe_top_k=2):
        super().__init__()
        self.ln1 = LayerNorm(hidden)
        self.ln2 = LayerNorm(hidden)
        self.heads = heads
        self.head_dim = hidden // heads
        self.is_moe = moe_experts > 0
        if use_parallel:
            self.qkv = ColumnParallelLinear(hidden, 3 * hidden,
                                            gather_output=False)
            self.proj = RowParallelLinear(hidden, hidden,
                                          input_is_parallel=True)
        else:
            self.qkv = Linear(hidden, 3 * hidden)
            self.proj = Linear(hidden, hidden)
        if self.is_moe:
            from ..parallel.moe import MoELayer

            self.moe = MoELayer(hidden, ffn, moe_experts, top_k=moe_top_k)
        elif use_parallel:
            self.fc1 = ColumnParallelLinear(hidden, ffn, gather_output=False)
            self.fc2 = RowParallelLinear(ffn, hidden, input_is_parallel=True)
        else:
            self.fc1 = Linear(hidden, ffn)
            self.fc2 = Linear(ffn, hidden)
        self.drop = Dropout(dropout)

    def forward(self, x, cache=None, position_offset=0):
        b, s, hdim = x.shape
        h = self.ln1(x)
        qkv = self.qkv(h).reshape([b, s, 3, self.heads, self.head_dim])
        q, k, v = ops.manipulation.unbind(qkv, axis=2)
        if cache is not None and hasattr(cache, "update_and_attend"):
            # external-cache hook: the serving engine's paged-KV view
            # writes K/V into its pool and runs ragged paged attention
            # (serving/kv_cache.py)
            attn, cache = cache.update_and_attend(q, k, v)
        elif isinstance(cache, DecodeCache):
            cache, k, v = cache_update(cache, k, v, position_offset)
            attn = masked_decode_attention(
                q, k, v, _decode_mask(position_offset, s, k.shape[1]))
        elif cache is not None:
            raise TypeError(
                "GPTBlock decode takes DecodeCache buffers "
                "(init_decode_caches); got %r" % type(cache).__name__)
        else:
            attn = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        attn = attn.reshape([b, s, hdim])
        x = x + self.drop(self.proj(attn))
        h = self.ln2(x)
        if self.is_moe:
            x = x + self.drop(self.moe(h))
        else:
            x = x + self.drop(self.fc2(F.gelu(self.fc1(h))))
        if cache is not None:
            return x, cache
        return x


class GPTModel(GenerationMixin, Layer):
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, ffn_size=None, max_seq_len=1024, dropout=0.0,
                 use_parallel=False, moe_experts=0, moe_every=2,
                 moe_top_k=2, moe_aux_coeff=0.01):
        """moe_experts > 0 turns every `moe_every`-th block into a
        GShard-style MoE block (expert-parallel over the dp mesh axis)."""
        super().__init__()
        ffn_size = ffn_size or 4 * hidden_size
        Emb = VocabParallelEmbedding if use_parallel else Embedding
        self.wte = Emb(vocab_size, hidden_size)
        self.wpe = Embedding(max_seq_len, hidden_size)
        self.blocks = LayerList([
            GPTBlock(hidden_size, num_heads, ffn_size, dropout, use_parallel,
                     moe_experts=(moe_experts
                                  if moe_experts and i % moe_every == 1
                                  else 0),
                     moe_top_k=moe_top_k)
            for i in range(num_layers)])
        self.ln_f = LayerNorm(hidden_size)
        self.vocab_size = vocab_size
        self.moe_aux_coeff = moe_aux_coeff

    def moe_aux_loss(self):
        """Sum of load-balancing losses from the MoE blocks this forward."""
        total = None
        for blk in self.blocks:
            if getattr(blk, "is_moe", False) and blk.moe.aux_loss is not None:
                total = (blk.moe.aux_loss if total is None
                         else total + blk.moe.aux_loss)
        return total

    def forward(self, input_ids, labels=None, caches=None,
                position_offset=0):
        import paddle_tpu as P

        b, s = input_ids.shape
        off = position_offset
        offv = off._value if hasattr(off, "_value") else off
        if getattr(offv, "ndim", 0):
            # per-row offsets (serving continuous batching): [B] -> [B, 1]
            # so the learned position lookup broadcasts to [B, S]
            off = P.Tensor(jnp.asarray(offv)[:, None].astype(jnp.int64))
        pos = P.arange(s, dtype="int64").unsqueeze(0) + off
        x = self.wte(input_ids) + self.wpe(pos)
        new_caches = []
        for i, blk in enumerate(self.blocks):
            if caches is not None:
                x, c = blk(x, caches[i], position_offset)
                new_caches.append(c)
            else:
                x = blk(x)
        x = self.ln_f(x)
        logits = P.matmul(x, self.wte.weight, transpose_y=True)
        if caches is not None:
            return logits, new_caches
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.vocab_size]), labels.reshape([-1]))
            aux = self.moe_aux_loss()
            if aux is not None:
                loss = loss + aux * self.moe_aux_coeff
            return loss
        return logits

    def generate_step(self, input_ids, caches, position_offset):
        """Single decode step with functional cache (GenerationMixin)."""
        return self.forward(input_ids, caches=caches,
                            position_offset=position_offset)

    def max_decode_len(self):
        return self.wpe.num_embeddings

    def paged_cache_spec(self):
        """KV geometry for the serving engine's paged cache."""
        return {"num_layers": len(self.blocks),
                "num_kv_heads": self.blocks[0].heads,
                "head_dim": self.blocks[0].head_dim,
                "dtype": str(self.wte.weight._value.dtype)}

    def init_decode_caches(self, batch, total_len):
        head_dim = self.blocks[0].head_dim
        heads = self.blocks[0].heads
        dt = self.wte.weight._value.dtype  # cache in the model's dtype
        return [DecodeCache(
            jnp.zeros((batch, total_len, heads, head_dim), dt),
            jnp.zeros((batch, total_len, heads, head_dim), dt))
            for _ in range(len(self.blocks))]
