"""Flagship model families (the reference ecosystem's ERNIE/GPT configs live
in PaddleNLP; the framework repo carries the layers. We ship the model zoo
in-tree so the distributed configs are testable)."""
from .ernie import (  # noqa: F401
    ErnieConfig,
    ErnieForPretraining,
    ErnieForSequenceClassification,
    ErnieModel,
)
from .gpt import GPTModel  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
