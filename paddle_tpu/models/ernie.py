"""ERNIE-style bidirectional transformer encoder (BASELINE.md north star
"ERNIE-3.0-base tokens/sec/chip").

Reference shape: the ERNIE family in the Paddle ecosystem is a
BERT-style encoder (token+position+segment embeddings, post-LN
transformer blocks, pooler, MLM + NSP/SOP heads) built on
paddle.nn.TransformerEncoder (reference python/paddle/nn/layer/
transformer.py). TPU-native: bidirectional attention through the same
flash kernel (causal=False), mpu-sharded projections under 'mp', batch
over 'dp' — the whole pretraining step compiles to one XLA module via
CompiledTrainStep.
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn.layer import Layer
from ..nn.layers.common import Dropout, Embedding, Linear
from ..nn.layers.container import LayerList
from ..nn.layers.norm import LayerNorm
from ..parallel.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)


class ErnieConfig:
    def __init__(self, vocab_size=40000, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, max_position_embeddings=512,
                 type_vocab_size=4, hidden_dropout_prob=0.1,
                 use_parallel=False, dtype="float32", fuse_qkv=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.use_parallel = use_parallel
        self.dtype = dtype
        # MXU shape optimization (same lever as LlamaConfig
        # fuse_attention_qkv, measured on v5e: K=N=768 sustains ~34
        # TF/s, N=2304 nearly doubles it): one [h, 3h] projection
        # instead of three narrow [h, h] ones. Single-device layout
        # only — the mp-sharded path keeps separate projections.
        self.fuse_qkv = fuse_qkv and not use_parallel

    @classmethod
    def tiny(cls, **kw):
        d = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                 num_attention_heads=4, intermediate_size=64,
                 max_position_embeddings=64, type_vocab_size=2,
                 hidden_dropout_prob=0.0)
        d.update(kw)
        return cls(**d)

    @classmethod
    def base(cls, **kw):  # ERNIE-3.0-base geometry
        return cls(**kw)


class ErnieSelfAttention(Layer):
    def __init__(self, c):
        super().__init__()
        self.heads = c.num_attention_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        Lin = (lambda i, o: ColumnParallelLinear(i, o, gather_output=False)
               ) if c.use_parallel else Linear
        self.fuse_qkv = getattr(c, "fuse_qkv", False)
        if self.fuse_qkv:
            self.qkv_proj = Linear(c.hidden_size, 3 * c.hidden_size)
        else:
            self.q_proj = Lin(c.hidden_size, c.hidden_size)
            self.k_proj = Lin(c.hidden_size, c.hidden_size)
            self.v_proj = Lin(c.hidden_size, c.hidden_size)
        if c.use_parallel:
            self.out_proj = RowParallelLinear(
                c.hidden_size, c.hidden_size, input_is_parallel=True)
        else:
            self.out_proj = Linear(c.hidden_size, c.hidden_size)

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        if self.fuse_qkv:
            qkv = self.qkv_proj(x).reshape(
                [b, s, 3, self.heads, self.head_dim])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            q = self.q_proj(x).reshape([b, s, self.heads, self.head_dim])
            k = self.k_proj(x).reshape([b, s, self.heads, self.head_dim])
            v = self.v_proj(x).reshape([b, s, self.heads, self.head_dim])
        # bidirectional: flash kernel with causal=False
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             is_causal=False)
        out = out.reshape([b, s, h])
        return self.out_proj(out)


class ErnieLayer(Layer):
    """Post-LN block (BERT/ERNIE convention, unlike Llama's pre-LN)."""

    def __init__(self, c):
        super().__init__()
        self.attn = ErnieSelfAttention(c)
        self.ln1 = LayerNorm(c.hidden_size)
        self.ln2 = LayerNorm(c.hidden_size)
        if c.use_parallel:
            self.fc1 = ColumnParallelLinear(
                c.hidden_size, c.intermediate_size, gather_output=False)
            self.fc2 = RowParallelLinear(
                c.intermediate_size, c.hidden_size,
                input_is_parallel=True)
        else:
            self.fc1 = Linear(c.hidden_size, c.intermediate_size)
            self.fc2 = Linear(c.intermediate_size, c.hidden_size)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, x, attn_mask=None):
        x = self.ln1(x + self.dropout(self.attn(x, attn_mask)))
        x = self.ln2(x + self.dropout(self.fc2(F.gelu(self.fc1(x)))))
        return x


class ErnieModel(Layer):
    def __init__(self, config):
        super().__init__()
        c = config
        self.config = c
        Emb = VocabParallelEmbedding if c.use_parallel else Embedding
        self.word_embeddings = Emb(c.vocab_size, c.hidden_size)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size)
        self.token_type_embeddings = Embedding(c.type_vocab_size,
                                               c.hidden_size)
        self.embed_ln = LayerNorm(c.hidden_size)
        self.embed_dropout = Dropout(c.hidden_dropout_prob)
        self.layers = LayerList(
            [ErnieLayer(c) for _ in range(c.num_hidden_layers)])
        self.pooler = Linear(c.hidden_size, c.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        from .. import arange

        b, s = input_ids.shape
        pos = arange(0, s, dtype="int32").unsqueeze(0)
        h = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            h = h + self.token_type_embeddings(token_type_ids)
        h = self.embed_dropout(self.embed_ln(h))
        for layer in self.layers:
            h = layer(h, attn_mask)
        pooled = F.tanh(self.pooler(h[:, 0]))
        return h, pooled


class ErnieForPretraining(Layer):
    """MLM + sentence-order heads (ERNIE pretraining objective)."""

    def __init__(self, config):
        super().__init__()
        c = config
        self.config = c
        self.ernie = ErnieModel(c)
        self.mlm_transform = Linear(c.hidden_size, c.hidden_size)
        self.mlm_ln = LayerNorm(c.hidden_size)
        if c.use_parallel:
            self.mlm_head = ColumnParallelLinear(
                c.hidden_size, c.vocab_size)
        else:
            self.mlm_head = Linear(c.hidden_size, c.vocab_size)
        self.sop_head = Linear(c.hidden_size, 2)

    def _maybe_fused_mlm_ce(self, h_mlm, masked_labels):
        """Mean MLM CE over valid tokens via the streaming lm_head+CE
        kernel (kernels/fused_ce.py) — the [tokens, 40000] logits never
        hit HBM in either direction. Same flag discipline as llama's
        _maybe_fused_ce: FLAGS_fused_lm_head_ce on, single-device
        layout, token count tiles, TRACED (compiled-step) path only.
        Unlike llama's lm_head, mlm_head carries a bias: it is folded
        exactly by augmenting h with a ones column and w with the bias
        row — padded a full 128 lanes so the kernel's H axis stays
        TPU-tile aligned. Returns None when the path does not apply."""
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..kernels.fused_ce import fused_ce_applies, fused_mean_ce

        hv = h_mlm._value if isinstance(h_mlm, Tensor) else h_mlm
        if not fused_ce_applies(hv, self.config.use_parallel):
            return None
        B, S, H = hv.shape
        T = B * S
        lv = masked_labels._value if isinstance(masked_labels, Tensor) \
            else jnp.asarray(masked_labels)
        hf = hv.reshape(T, H)
        w = self.mlm_head.weight._value
        bias = self.mlm_head.bias._value
        pad = 128
        h_aug = jnp.concatenate(
            [hf, jnp.zeros((T, pad), hf.dtype).at[:, 0].set(1.0)], axis=1)
        w_aug = jnp.concatenate(
            [w, jnp.zeros((pad, w.shape[1]), w.dtype)
             .at[0].set(bias.astype(w.dtype))], axis=0)
        return Tensor(fused_mean_ce(h_aug, w_aug, lv.reshape(T)))

    def forward_head_loss(self, h, masked_labels):
        """Fused MLM loss tail over final hidden states (mean CE over
        non-ignored tokens — forward(masked_labels=...)'s contract for
        the MLM term). Returns None so callers fall back to the
        materialized mlm_head + cross_entropy path when the kernel does
        not apply (VERDICT round-5 #2: same protocol as llama's
        forward_head_loss)."""
        return self._maybe_fused_mlm_ce(
            self.mlm_ln(F.gelu(self.mlm_transform(h))), masked_labels)

    def forward(self, input_ids, token_type_ids=None, masked_labels=None,
                sop_labels=None):
        h, pooled = self.ernie(input_ids, token_type_ids)
        h_mlm = self.mlm_ln(F.gelu(self.mlm_transform(h)))
        sop = self.sop_head(pooled)
        if masked_labels is None:
            return self.mlm_head(h_mlm), sop
        loss = self._maybe_fused_mlm_ce(h_mlm, masked_labels)
        if loss is None:
            mlm = self.mlm_head(h_mlm)
            loss = F.cross_entropy(
                mlm.reshape([-1, self.config.vocab_size]),
                masked_labels.reshape([-1]), ignore_index=-100)
        if sop_labels is not None:
            loss = loss + F.cross_entropy(sop, sop_labels)
        return loss


class ErnieForSequenceClassification(Layer):
    def __init__(self, config, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.classifier = Linear(config.hidden_size, num_classes)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits
