"""paddle.hub namespace (reference python/paddle/hub.py: re-exports the
hapi.hub entrypoint API)."""
from .hapi.hub import help, list, load  # noqa: F401,A004
