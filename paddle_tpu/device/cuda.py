"""paddle.device.cuda — stream/event API surface for ported code.

Reference python/paddle/device/cuda/__init__.py. Under PJRT the runtime
owns streams; `synchronize` maps to draining outstanding work, the
stream/event objects are inert records (documented deviation — the
scheduling they tune by hand is XLA's latency-hiding scheduler's job).
"""
from __future__ import annotations

__all__ = ["Stream", "Event", "current_stream", "synchronize",
           "device_count", "get_device_properties", "empty_cache"]


class Stream:
    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()


class Event:
    def __init__(self, enable_timing=False, blocking=False,
                 interprocess=False):
        self._pending = False

    def record(self, stream=None):
        self._pending = True
        # PJRT dispatch is async but ordered; by the time user code can
        # query, prior work on the record point is complete
        self._pending = False

    def query(self):
        """True when complete — including never-recorded events
        (cudaEventQuery semantics: unrecorded queries as success)."""
        return not self._pending

    def synchronize(self):
        synchronize()


_current = Stream()


def current_stream(device=None):
    return _current


def synchronize(device=None):
    """Drain outstanding device work (reference cuda.synchronize)."""
    import jax
    import jax.numpy as jnp

    jax.block_until_ready(jnp.zeros(()))


def device_count():
    import jax

    return len(jax.devices())


def _device_index(device):
    if device is None:
        return 0
    if isinstance(device, int):
        return device
    if isinstance(device, str):
        # accepted paddle forms: 'gpu:0' / 'tpu:0' / 'cpu' / '0'
        tail = device.rsplit(":", 1)[-1]
        return int(tail) if tail.isdigit() else 0
    for attr in ("get_device_id", "device_id"):
        f = getattr(device, attr, None)
        if f is not None:
            return f() if callable(f) else f
    raise ValueError("unrecognized device spec %r" % (device,))


def get_device_properties(device=None):
    import jax

    idx = _device_index(device)
    devs = jax.devices()
    if not 0 <= idx < len(devs):
        raise ValueError(
            "device index %d out of range (have %d devices)"
            % (idx, len(devs)))
    d = devs[idx]
    stats = d.memory_stats() if hasattr(d, "memory_stats") else None

    class _Props:
        name = getattr(d, "device_kind", d.platform)
        major, minor = 0, 0
        total_memory = (stats or {}).get("bytes_limit", 0)
        multi_processor_count = 1

    return _Props()


def empty_cache():
    pass  # XLA buffer assignment owns memory
