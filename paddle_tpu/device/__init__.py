"""paddle.device namespace.

Parity: reference python/paddle/device/__init__.py (set_device/
get_device/place queries + per-vendor is_compiled_with_*). TPU mapping:
PJRT owns contexts and streams; the `cuda` submodule exposes the
reference's stream/event API as documented no-ops so ported code runs
(synchronization is XLA's async-dispatch + block_until_ready).
"""
from __future__ import annotations

from ..core.place import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    CustomPlace,
    TPUPlace,
    device_count,
    get_all_custom_device_type,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_custom_device,
    is_compiled_with_tpu,
    place_for,
    register_custom_device,
    register_custom_device_factory,
    register_fake_cpu_device,
    set_device,
)
from . import cuda  # noqa: F401


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_cinn():
    # XLA plays CINN's role (SURVEY layer 13); report False for the
    # literal CINN bridge the reference means
    return False


def is_compiled_with_mkldnn():
    return False


def get_cudnn_version():
    return None  # no cuDNN on this stack


def get_available_device():
    import jax

    return ["%s:%d" % (d.platform, d.id) for d in jax.devices()]


def get_available_custom_device():
    return get_all_custom_device_type()
