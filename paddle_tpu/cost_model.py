"""paddle.cost_model (reference python/paddle/cost_model/cost_model.py).

CostModel estimates per-op and whole-program cost. The reference
profiles a static Program on device and keeps a static table of op
times; the TPU build delegates to the auto-parallel cost model
(distributed/auto_parallel/cost_model.py), which reasons in FLOPs +
bytes over the mesh — the quantities XLA scheduling actually follows.
"""
from __future__ import annotations

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        from .distributed.auto_parallel.cost_model import CostEstimator

        self._cm = CostEstimator()

    def get_static_op_time(self, op_name, forward=True, dtype="float32"):
        """Rough per-op cost from the analytic model (reference keys a
        profiled JSON table; analog documented)."""
        return {"op_name": op_name, "forward": forward, "dtype": dtype,
                "analytic": True}

    def profile_measure(self, main_program=None, startup_program=None,
                        device="tpu", fetch_cost_list=("time",),
                        feed=None):
        """Measure a program by running it (reference profile_measure).
        Accepts our static Program (+ a feed dict for its data vars);
        returns wall-time per run."""
        import time

        from .static import Executor

        exe = Executor()
        if startup_program is not None:
            exe.run(startup_program)
        t0 = time.perf_counter()
        if main_program is not None:
            exe.run(main_program, feed=feed)
        return {"time": (time.perf_counter() - t0) * 1000.0}

    def __getattr__(self, name):
        if name.startswith("_"):
            # never proxy dunders/privates: unpickling creates the object
            # without __init__, and proxying '_cm' itself would recurse
            raise AttributeError(name)
        return getattr(self._cm, name)
